"""Control-plane driver — Python face of the native C++ operator.

The reconciler itself is the compiled ``tpu-operator`` binary
(native/controlplane/, reference parity: controllers/dgljob_controller.go);
this package supplies what surrounds it:

- :mod:`~.api`         TPUGraphJob construction helpers (CRD-shaped dicts)
- :mod:`~.controller`  the reconcile loop: snapshot state -> run binary ->
                       apply actions to a cluster store
- :mod:`~.cluster`     FakeCluster, the in-process store used by tests
                       (envtest-without-kubelet parity: suite_test.go) and
                       as the model for a kube API-server shim
"""

from dgl_operator_tpu.controlplane.api import (TPUGraphJob, replica_spec,
                                               simple_job)
from dgl_operator_tpu.controlplane.cluster import FakeCluster
from dgl_operator_tpu.controlplane.controller import (Controller,
                                                      operator_binary,
                                                      watcher_binary)

__all__ = [
    "TPUGraphJob", "replica_spec", "simple_job",
    "FakeCluster", "Controller", "operator_binary", "watcher_binary",
]
