"""Multi-host rendezvous — hostfile -> ``jax.distributed.initialize``.

The reference bootstraps its cluster in three stages: the operator
renders pod IPs into a ConfigMap hostfile (``ip 30050 podname slots=N``,
controllers/dgljob_controller.go:1416-1437, format docs/design.md:373),
``revise_hostfile.py`` rewrites it per framework, and
``torch.distributed.launch`` does TCP rendezvous on the first entry
(python/dglrun/tools/launch.py:135-152). The TPU equivalent collapses
all of that into ``jax.distributed.initialize(coordinator, n, rank)``
with the coordinator at the first hostfile entry — after which the
global device mesh (ICI + DCN) simply exists; there are no server
processes to spawn (SURVEY.md §2 "TPU-native equivalent").

Env contract (rendered by the operator, mirroring ``DGL_OPERATOR_*``
from dgljob_controller.go:58-63):

    TPU_OPERATOR_HOSTFILE_PATH   path to the hostfile
    TPU_OPERATOR_RANK            this process's line index (else matched
                                 by hostname)
    TPU_OPERATOR_PHASE_ENV       workflow phase (launcher/partitioner/…)
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import List, Optional

HOSTFILE_ENV = "TPU_OPERATOR_HOSTFILE_PATH"
RANK_ENV = "TPU_OPERATOR_RANK"
PHASE_ENV = "TPU_OPERATOR_PHASE_ENV"
# elastic incarnation epoch (ISSUE 13): exported by the elastic driver
# (launcher/elastic.py) on every shrink/regrow edge, read by
# runtime/checkpoint.py to fence checkpoint publication. Lives here —
# the one env-contract module both the stdlib-only launcher and the
# jax-importing runtime already depend on — so neither has to import
# the other for a constant.
FENCE_EPOCH_ENV = "TPU_OPERATOR_ELASTIC_EPOCH"
DEFAULT_PORT = 30050  # parity: DGL_PORT api/v1alpha1/dgljob_types.go


@dataclasses.dataclass
class HostEntry:
    ip: str
    port: int
    name: str
    slots: int

    @property
    def addr(self) -> str:
        return f"{self.ip}:{self.port}"


def parse_hostfile(path: str) -> List[HostEntry]:
    """Parse the operator hostfile: ``ip port podname slots=N`` per line
    (launcher lines excluded by the operator already; tolerate and skip
    them like watcher-loop does, watcher-loop/app/server.go:108-120)."""
    entries: List[HostEntry] = []
    with open(path) as f:
        for ln in f:
            parts = ln.split()
            if not parts or parts[0].startswith("#"):
                continue
            name = parts[2] if len(parts) > 2 else parts[0]
            if name.endswith("launcher"):
                continue
            slots = 1
            for p in parts[3:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            entries.append(HostEntry(parts[0], int(parts[1]) if len(parts) > 1
                                     else DEFAULT_PORT, name, slots))
    return entries


def my_rank(entries: List[HostEntry]) -> Optional[int]:
    if RANK_ENV in os.environ:
        return int(os.environ[RANK_ENV])
    host = socket.gethostname()
    for i, e in enumerate(entries):
        if e.name == host or e.ip == host:
            return i
    return None


def initialize_from_hostfile(path: Optional[str] = None,
                             rank: Optional[int] = None) -> int:
    """Bring up jax.distributed from the hostfile; returns this
    process's rank. No-op (rank 0) for single-host jobs — the
    ``partitionMode: Skip`` / launcher-only path (dglrun:119-131)."""
    path = path or os.environ.get(HOSTFILE_ENV)
    if not path or not os.path.exists(path):
        return 0
    entries = parse_hostfile(path)
    if len(entries) <= 1:
        return 0
    if rank is None:
        rank = my_rank(entries)
    if rank is None:
        raise RuntimeError(
            f"cannot determine rank: hostname {socket.gethostname()!r} not "
            f"in hostfile and {RANK_ENV} unset")
    import jax
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # CPU fake-slice shape (tests / local bring-up): cross-process
        # collectives need the gloo transport; TPU slices use ICI/DCN
        # and ignore this knob.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
    jax.distributed.initialize(coordinator_address=entries[0].addr,
                               num_processes=len(entries),
                               process_id=rank)
    return rank


def write_hostfile(path: str, entries: List[HostEntry]) -> None:
    with open(path, "w") as f:
        for e in entries:
            f.write(f"{e.ip} {e.port} {e.name} slots={e.slots}\n")


def revise_hostfile(src: str, dst: str, style: str = "jax",
                    num_servers: int = 1) -> str:
    """Framework-specific hostfile rewrite — capability parity with
    tools/revise_hostfile.py:8-46 (``dgl`` -> "ip port"; ``dglke`` ->
    "ip port num_servers"; ``jax`` -> coordinator-first "ip:port")."""
    entries = parse_hostfile(src)
    with open(dst, "w") as f:
        for e in entries:
            if style == "dgl":
                f.write(f"{e.ip} {e.port}\n")
            elif style == "dglke":
                f.write(f"{e.ip} {e.port} {num_servers}\n")
            elif style == "jax":
                f.write(f"{e.ip}:{e.port}\n")
            else:
                raise ValueError(style)
    return dst
