"""Graph-partitioned data-parallel training step.

The reference's data parallelism: each worker trains on mini-batches
sampled from its own graph partition, dense gradients are allreduced by
PyTorch DDP over gloo per backward bucket
(examples/GraphSAGE_dist/code/train_dist.py:187-192,267-270). The
TPU-native form is one jit'd SPMD program over the ``dp`` mesh axis:
every mesh slot consumes its partition's batch, and the gradient
``psum`` is a single fused ICI collective XLA schedules inside the
backward pass — the role DDP's bucketing plays, without the buckets.

``make_dp_train_step`` builds that program once for any (loss_fn,
optimizer); batches are pytrees whose leaves carry a leading mesh-slot
axis (stacked per-partition batches, see ``stack_batches``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgl_operator_tpu.parallel.mesh import DP_AXIS


def stack_batches(batches):
    """Stack per-partition host batches into one pytree with a leading
    dp axis (the host-side analogue of DistDataLoader handing each
    worker its own batch, train_dist.py:177-182)."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def make_dp_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                       mesh: Mesh, donate: bool = True):
    """Build the jitted SPMD step.

    loss_fn(params, batch) -> scalar loss for ONE mesh slot's batch.
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where ``batch`` leaves have leading dim == mesh dp size and params /
    opt_state are replicated.
    """

    def _shard_step(params, opt_state, batch):
        # each slot's block keeps a size-1 leading dp axis; drop it so
        # loss_fn sees the per-partition batch directly
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # DDP-equivalent: mean-reduce grads (and the loss metric) over dp
        grads = jax.lax.pmean(grads, DP_AXIS)
        loss = jax.lax.pmean(loss, DP_AXIS)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # shard_map specs: params/opt_state replicated, batch split on dim 0
    def batch_spec(batch):
        return jax.tree.map(lambda _: P(DP_AXIS), batch)

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def step(params, opt_state, batch):
        f = jax.shard_map(
            _shard_step, mesh=mesh,
            in_specs=(P(), P(), batch_spec(batch)),
            out_specs=(P(), P(), P()),
            check_vma=False)
        return f(params, opt_state, batch)

    return step


def make_dp_eval_step(metric_fn: Callable, mesh: Mesh):
    """Replicated-params eval over dp-sharded batches; metrics are
    (sum, count) pairs psum'd over the axis so global averages are exact
    even with uneven masking."""

    def _shard_eval(params, batch):
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        s, c = metric_fn(params, batch)
        return jax.lax.psum(s, DP_AXIS), jax.lax.psum(c, DP_AXIS)

    @jax.jit
    def evaluate(params, batch):
        f = jax.shard_map(
            _shard_eval, mesh=mesh,
            in_specs=(P(), jax.tree.map(lambda _: P(DP_AXIS), batch)),
            out_specs=(P(), P()),
            check_vma=False)
        s, c = f(params, batch)
        return s / jnp.maximum(c, 1)

    return evaluate


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device.

    Multi-process (multi-controller SPMD): every process passes the SAME
    host value (same init seed / same checkpoint) and contributes its
    addressable replicas via ``make_array_from_process_local_data`` —
    ``device_put`` cannot target non-addressable devices."""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sh, np.asarray(x)), tree)


def dp_shard(mesh: Mesh, tree):
    """Place a stacked batch pytree with leading dim over dp.

    Single process: leaves carry the FULL leading dp extent. Multi-
    process: each process passes only the rows for ITS mesh slots
    (contiguous block, process order) and the global array is assembled
    across processes (the reference analogue: each worker pod holds only
    its own partition, train_dist.py:270-277)."""
    def put(x):
        spec = P(DP_AXIS, *([None] * (np.ndim(x) - 1)))
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        return jax.make_array_from_process_local_data(sh, np.asarray(x))
    return jax.tree.map(put, tree)
