"""Graph-partitioned data-parallel training step.

The reference's data parallelism: each worker trains on mini-batches
sampled from its own graph partition, dense gradients are allreduced by
PyTorch DDP over gloo per backward bucket
(examples/GraphSAGE_dist/code/train_dist.py:187-192,267-270). The
TPU-native form is one jit'd SPMD program over the ``dp`` mesh axis:
every mesh slot consumes its partition's batch, and the gradient
``psum`` is a single fused ICI collective XLA schedules inside the
backward pass — the role DDP's bucketing plays, without the buckets.

``make_dp_train_step`` builds that program once for any (loss_fn,
optimizer); batches are pytrees whose leaves carry a leading mesh-slot
axis (stacked per-partition batches, see ``stack_batches``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgl_operator_tpu.parallel.mesh import DP_AXIS, shard_map
from dgl_operator_tpu.parallel import shardrules


def stack_batches(batches):
    """Stack per-partition host batches into one pytree with a leading
    dp axis (the host-side analogue of DistDataLoader handing each
    worker its own batch, train_dist.py:177-182)."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def wus_sharded_leaf(x) -> bool:
    """Legacy all-params placement rule (kept as a public seam): array
    leaves of the optimizer state shard over dp, scalar leaves (adam's
    step count) stay replicated. The general form — per-param rules,
    moments inheriting their param's spec by tree path — lives in
    ``parallel.shardrules`` and is what this module derives placement
    from now."""
    return len(getattr(x, "shape", ())) > 0


def param_allgather_start(shard, axis, dim: "int | None" = None):
    """Issue the all-gather that re-materializes a full parameter from
    its persistent shard (the ZeRO-3 gather-at-use pull). ``dim=None``
    gathers a flat element shard back into the flat padded vector;
    an integer ``dim`` gathers a tensor-parallel block along that dim.
    Returns the in-flight gathered value — pin it behind independent
    compute with :func:`param_allgather_done` before slicing it to the
    logical shape, so XLA keeps the collective and the compute as
    separate subgraphs and can run the gather underneath (the
    parallel/halo.py start/done discipline)."""
    if dim is None:
        return jax.lax.all_gather(shard, axis, tiled=True)
    return jax.lax.all_gather(shard, axis, axis=dim, tiled=True)


def param_allgather_done(full, anchor=None):
    """Complete a :func:`param_allgather_start`: one
    ``optimization_barrier`` makes the gathered value depend on
    ``anchor`` (compute or an earlier gather's result), so the wait
    lands after the work the gather should hide under instead of right
    next to its own issue. ``anchor=None`` passes through — the head
    of a gather pipeline has nothing to hide under yet."""
    if anchor is None:
        return full
    full, _ = jax.lax.optimization_barrier((full, anchor))
    return full


def _validate_dp_rules(rules, mesh: "Mesh | None" = None,
                       zero_stage: int = 1):
    """Rules for the dense DP path: under ``zero_stage=1`` they may
    only target the dp axis (a rule naming any other axis would be
    tensor parallelism, which the replicated-params step does not
    implement); under ``zero_stage=3`` any axis PRESENT ON THE MESH is
    legal (dp selects the flat ZeRO shard treatment, a model-parallel
    axis selects dim sharding) — an axis the mesh does not carry is
    loud either way, not silently replicated."""
    z3 = zero_stage == 3
    for pat, spec in rules:
        ps = shardrules.to_pspec(spec)
        for ax in shardrules.spec_axes(ps):
            if z3:
                if mesh is not None and ax not in mesh.axis_names:
                    raise ValueError(
                        f"shard_rules entry {pat!r} names axis {ax!r} "
                        f"which is not on the mesh (axes: "
                        f"{tuple(mesh.axis_names)!r})")
            elif ax != DP_AXIS:
                raise ValueError(
                    f"shard_rules entry {pat!r} names axis {ax!r}; "
                    f"the DP train step only supports {DP_AXIS!r} "
                    "(ZeRO-style weight-update sharding) or None "
                    "(replicated); pass zero_stage=3 for rule-driven "
                    "tensor parallelism")


def make_dp_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                       mesh: Mesh, donate: bool = True,
                       shard_update: bool = False,
                       shard_rules: "tuple | None" = None,
                       per_step_keys: "tuple | None" = None,
                       staged_keys: "tuple | None" = None,
                       fused_exchange: "Callable | None" = None,
                       index_carry: bool = False,
                       with_stats: bool = False,
                       zero_stage: int = 1,
                       gather_depth: int = 2,
                       prog_name: str = "dp_train_step"):
    """Build the jitted SPMD step.

    loss_fn(params, batch) -> scalar loss for ONE mesh slot's batch.
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where ``batch`` leaves have leading dim == mesh dp size and params
    are replicated.

    ``staged_keys`` is the decoupled-pipeline face (the DistTrainer
    halo prefetch stage, runtime/dist.py): the step's signature becomes
    ``step(params, opt_state, batch, staged)`` where ``staged`` is a
    dict holding exactly those keys (dp-sharded like the batch),
    produced by an upstream jitted stage — and ``staged`` is ALWAYS
    donated, because a staging buffer is consumed by exactly one step
    and donating it is what keeps pipeline HBM flat at the staging
    depth instead of growing a buffer per in-flight batch. The batch
    itself is never donated (it carries step-invariant device-resident
    members like the feature shards). Not composable with
    ``per_step_keys`` (the scan stacks per-step members itself).

    ``per_step_keys`` turns the step into a K-step ``lax.scan`` (the
    DistTrainer face of ``TrainConfig.steps_per_call``): ``batch`` must
    be a dict whose listed keys carry a K axis after the dp one
    (``[P, K, ...]``); every other key is step-invariant (features,
    CSR shards). Each scan iteration runs the full grad + pmean +
    update; the returned loss is the last step's. Collectives inside
    ``lax.scan`` under shard_map are ordinary XLA collectives — same
    program K times, one dispatch. Not composable with
    ``shard_update`` (the WUS reduce-scatter path stays per-dispatch).

    ``shard_update=True`` enables cross-replica weight-update sharding
    (Xu et al., arXiv:2004.13336 — the ZeRO-style dp-redundancy
    elimination, PAPERS.md): gradients are ``psum_scatter``'d so each
    dp slot owns 1/n of every parameter's flattened elements, the
    optimizer (and its ENTIRE state — Adam moments live sharded, 1/n
    per device) updates only that shard, and the fresh shards are
    ``all_gather``'d back into replicated params. Same math as the
    replicated form for any elementwise optimizer — reduce-scatter +
    all-gather IS an allreduce — at 1/n the optimizer-state HBM and
    1/n the update FLOPs per device. Build the sharded state with the
    returned step's ``init_opt_state(params)``.

    ``fused_exchange`` is the in-program async-collective face (the
    DistTrainer fused pipeline, ``TrainConfig.pipeline_mode="fused"``):
    requires ``staged_keys``, and the step's signature becomes
    ``step(params, opt_state, batch, staged, next_ebatch) ->
    (params, opt_state, loss, next_recv)``. Inside the shard body the
    NEXT batch's halo collective is ISSUED first
    (``fused_exchange(batch, next_ebatch)`` — the async start), the
    DDP update runs on this batch's already-staged payload, and the
    in-flight handle is pinned behind the loss through
    ``parallel.halo.halo_exchange_done`` (one optimization barrier) so
    XLA cannot sink the done next to the start — the collective and
    the compute stay independent subgraphs joined only at the outputs,
    which is what lets the scheduler run the a2a under the MXU work.
    ``next_ebatch`` is ALWAYS donated (one batch's request table, dead
    after the start), like ``staged``; the returned ``next_recv`` is
    the staging-ring buffer the step at t+K consumes.

    ``index_carry`` is the device-resident stream face (the device
    sampler's zero-host-sync steady state): the signature becomes
    ``step(params, opt_state, batch, idx) -> (params, opt_state,
    loss, idx + 1)`` where ``idx`` is a replicated, ALWAYS-donated
    device scalar the loop threads back in. ``loss_fn`` sees it as
    ``batch["step_idx"]`` and indexes the epoch's device-resident
    seed bank with it — no per-step host staging at all. Not
    composable with ``per_step_keys`` / ``staged_keys`` (the scan and
    the staging ring carry their own per-step members).

    ``with_stats`` is the model-health face (ISSUE 15, obs/quality.py):
    the step additionally returns a small jit-computed stats pytree —
    per-partition loss and non-finite gradient counts (``[P]``, the
    partition attribution of the numerics sentry), plus replicated
    global grad/param norms and the update ratio. Appended as the LAST
    return value of every signature variant. The stats are pure
    read-only consumers of intermediates the update already computes
    (loss before the pmean, the pmean'd grads, the updates, the fresh
    params), so the parameter trajectory is BIT-IDENTICAL to
    ``with_stats=False`` and — on the non-WUS paths — no additional
    collective is emitted (per-partition members ride the dp
    out-spec). The WUS path psums its sharded-leaf partial norms (a
    few scalars per step). Pinned by tests/test_quality.py.

    ``zero_stage=3`` makes the parameter sharding PERSISTENT (ZeRO-3 /
    fully-sharded data parallel): between steps every rule-selected
    param lives as its 1/N shard only — a flat element shard over dp
    (the weight-update-sharding storage form) or a tensor-parallel dim
    block over a model-parallel mesh axis — and full values exist
    transiently inside the step via per-param
    ``param_allgather_start``/``param_allgather_done`` pairs. All the
    starts are issued as one independent subgraph up front; each done
    is pinned behind the gather ``gather_depth`` positions earlier, so
    at most ``gather_depth`` gather buffers are live at once and every
    later gather hides under the compute consuming the earlier params.
    Gradients take the reduce-scatter half only (no trailing
    all-gather re-materializes params), so per-step traffic AND
    persistent residency drop. The math is the replicated run's
    bit-for-bit: flat shards reuse the exact psum_scatter/n +
    elementwise-update algebra of ``shard_update`` above, and dim
    blocks slice the pmean'd gradient so each slot applies precisely
    the rows of the replicated update it owns. The step's params
    argument/return is the STORAGE tree; convert with the attached
    seams: ``step.shard_params(params)`` (logical -> placed storage,
    must run before the first step), ``step.gather_params(storage)``
    (-> full replicated params for eval/serving),
    ``step.logical_state(storage, opt_state)`` (-> host logical,
    padding-free trees — the mesh-shape-invariant checkpoint form) and
    ``step.adopt_state(logical_params, logical_opt)`` (re-pad +
    re-place a logical checkpoint on THIS mesh, whatever mesh shape
    wrote it). ``zero_stage=3`` with neither ``shard_update`` nor
    ``shard_rules`` shards every param (``((".*", "dp"),)``).

    ``shard_rules`` is the general, rule-driven form of the same mode
    (parallel/shardrules.py): ordered ``(regex, spec)`` pairs matched
    first-match-wins against each param's '/'-joined tree path. A
    param whose spec names the dp axis gets the weight-update-sharding
    treatment above (its optimizer state lives 1/n per device); a
    replicated spec keeps the plain pmean update. ``shard_update=True``
    is exactly ``shard_rules=(('.*', 'dp'),)``. Scalar params and
    scalar state leaves (Adam's count) always stay replicated. The
    placement the step derives for any state is exposed as
    ``step.opt_placement(opt_state, params)`` — the checkpoint restore
    path re-places restored host arrays with it.
    """
    if shard_update and shard_rules is not None:
        raise ValueError("pass either shard_update=True (all params) "
                         "or shard_rules (per-param), not both")
    from dgl_operator_tpu.autotune.knobs import validate
    zero_stage = int(validate("zero_stage", zero_stage))
    gather_depth = int(validate("gather_depth", gather_depth))
    if zero_stage == 3 and not shard_update and shard_rules is None:
        shard_update = True   # ZeRO-3 default: shard every param
    if shard_update:
        shard_rules = ((".*", DP_AXIS),)
    if shard_rules is not None:
        _validate_dp_rules(shard_rules, mesh=mesh,
                           zero_stage=zero_stage)
        shard_update = True   # rules engage the WUS code path below
    if per_step_keys and shard_update:
        raise ValueError("per_step_keys multi-step scan does not "
                         "compose with shard_update")
    if per_step_keys and staged_keys:
        raise ValueError("staged_keys (decoupled staging buffers) does "
                         "not compose with per_step_keys (the K-step "
                         "scan stacks its own per-step members)")
    if fused_exchange is not None and not staged_keys:
        raise ValueError("fused_exchange requires staged_keys (the "
                         "fused step consumes this batch's staged "
                         "payload while issuing the next batch's "
                         "exchange)")
    if index_carry and (per_step_keys or staged_keys):
        raise ValueError("index_carry (device-resident stream index) "
                         "does not compose with per_step_keys or "
                         "staged_keys (the scan and the staging ring "
                         "carry their own per-step members)")
    n = int(mesh.shape[DP_AXIS])

    def _flat_pad(x):
        flat = x.reshape(-1)
        pad = (-flat.size) % n
        return jnp.pad(flat, (0, pad)) if pad else flat

    def _my_shard(x):
        flat = _flat_pad(x)
        k = flat.size // n
        return jax.lax.dynamic_slice(
            flat, (jax.lax.axis_index(DP_AXIS) * k,), (k,))

    def _selection(params):
        """Per-param WUS selection from the rules: True where the
        matched spec shards over dp (pytree of Python bools — static,
        derivable from tracers)."""
        specs = shardrules.match_partition_rules(shard_rules, params)
        return jax.tree.map(lambda s: DP_AXIS in jax.tree.leaves(
            tuple(s)), specs)

    def _param_specs(params):
        """Accounting/placement view of the params under the rules
        (scalars replicated, per shardrules contract)."""
        return shardrules.match_partition_rules(shard_rules, params)

    # -- zero_stage=3: persistent param shards ------------------------
    # the step body cannot derive the LOGICAL shapes from its storage
    # tracers (a flat shard of a small param degenerates to a scalar
    # and would flip the rule selection), so the classification is
    # recorded host-side — by shard_params / init_opt_state /
    # adopt_state — into this closure cell before the first trace.
    _z3: dict = {}

    def _z3_classify(params):
        """Per-leaf storage plan from the rules, computed on a LOGICAL
        tree: 'repl' (storage == logical), 'flat' (1/N element shard
        over dp — the WUS form, persistent), or 'dim' (tensor-parallel
        block over one model-parallel mesh axis)."""
        specs = shardrules.match_partition_rules(shard_rules, params)
        paths, metas = [], []
        for (path, leaf), (_, spec) in zip(shardrules.tree_paths(params),
                                           shardrules.tree_paths(specs)):
            shape = tuple(int(s) for s in leaf.shape)
            axes = shardrules.spec_axes(spec)
            if not axes:
                m = {"kind": "repl", "shape": shape, "spec": P()}
            elif DP_AXIS in axes:
                if len(axes) > 1:
                    raise ValueError(
                        f"zero_stage=3 param {path!r}: spec {spec} "
                        f"combines {DP_AXIS!r} (the flat ZeRO shard "
                        "treatment) with model-parallel axes; give "
                        "each param one or the other")
                m = {"kind": "flat", "shape": shape,
                     "spec": P(DP_AXIS)}
            else:
                entries = tuple(spec)
                sdims = [i for i, e in enumerate(entries) if e]
                ax = entries[sdims[0]] if len(sdims) == 1 else None
                if isinstance(ax, (tuple, list)):
                    ax = ax[0] if len(ax) == 1 else None
                if ax is None:
                    raise ValueError(
                        f"zero_stage=3 TP param {path!r}: exactly one "
                        f"dim sharded over one axis is supported, got "
                        f"spec {spec}")
                d, msize = sdims[0], int(mesh.shape[ax])
                m = {"kind": "dim", "shape": shape, "dim": d,
                     "axis": ax, "msize": msize,
                     "pad_to": -(-shape[d] // msize) * msize,
                     "spec": shardrules.to_pspec(spec)}
            paths.append(path)
            metas.append(m)
        return paths, metas, jax.tree_util.tree_structure(params)

    def _z3_record(params):
        paths, metas, treedef = _z3_classify(params)
        _z3.update(paths=paths, metas=metas, treedef=treedef)

    def _z3_metas():
        if not _z3:
            raise RuntimeError(
                "zero_stage=3 step used before its storage plan was "
                "recorded: call step.shard_params(params) / "
                "step.init_opt_state(params) / step.adopt_state(...) "
                "before the first step")
        return _z3["metas"]

    def _storage_spec_tree():
        metas = _z3_metas()
        return jax.tree_util.tree_unflatten(
            _z3["treedef"], [m["spec"] for m in metas])

    def _z3_materialize(storage_leaves, metas):
        """Gather-at-use: issue EVERY param's all-gather up front (one
        independent subgraph — the list comprehension is deliberate),
        then complete them in order with each done pinned behind the
        gather ``gather_depth`` positions earlier, bounding live
        staging buffers to the window while later gathers hide under
        the compute consuming earlier params."""
        from dgl_operator_tpu.obs.comm import register_collective

        # one aggregate ledger record for the whole gather pipeline
        # (per-leaf records would overwrite each other under the
        # (program, op, axis) key): total re-materialized bytes — for
        # an all-flat tree this is exactly
        # shardrules.zero3_bytes_per_slot(params, n) * n
        register_collective(
            "param_allgather", DP_AXIS,
            sum(x.size * (m["msize"] if m["kind"] == "dim" else n)
                * x.dtype.itemsize
                for x, m in zip(storage_leaves, metas)
                if m["kind"] != "repl"),
            fused_depth=gather_depth)
        starts = [param_allgather_start(x, DP_AXIS)
                  if m["kind"] == "flat" else
                  (param_allgather_start(x, m["axis"], dim=m["dim"])
                   if m["kind"] == "dim" else x)
                  for x, m in zip(storage_leaves, metas)]
        fulls = []
        for i, (h, m) in enumerate(zip(starts, metas)):
            anchor = fulls[i - gather_depth] if i >= gather_depth \
                else None
            full = param_allgather_done(h, anchor)
            if m["kind"] == "flat":
                size = int(np.prod(m["shape"], dtype=int))
                full = full[:size].reshape(m["shape"])
            elif m["kind"] == "dim" and m["pad_to"] != \
                    m["shape"][m["dim"]]:
                full = jax.lax.slice_in_dim(
                    full, 0, m["shape"][m["dim"]], axis=m["dim"])
            fulls.append(full)
        return jax.tree_util.tree_unflatten(_z3["treedef"], fulls)

    def _z3_gview(g, m):
        """One logical gradient -> its storage view: flat shards take
        the reduce-scatter half of the allreduce (EXACTLY the WUS
        algebra, so the trajectory is bit-identical); dim blocks slice
        the pmean'd gradient (replicated over the model axis) at their
        own block offset, zero-padding the sharded dim first."""
        if m["kind"] == "flat":
            return jax.lax.psum_scatter(
                _flat_pad(g), DP_AXIS, scatter_dimension=0,
                tiled=True) / n
        g = jax.lax.pmean(g, DP_AXIS)
        if m["kind"] == "repl":
            return g
        d, block = m["dim"], m["pad_to"] // m["msize"]
        if m["pad_to"] != m["shape"][d]:
            widths = [(0, 0)] * len(m["shape"])
            widths[d] = (0, m["pad_to"] - m["shape"][d])
            g = jnp.pad(g, widths)
        lo = jax.lax.axis_index(m["axis"]) * block
        return jax.lax.dynamic_slice_in_dim(g, lo, block, axis=d)

    def _z3_sq(tree, metas):
        """Global sum of squares of a storage-shaped tree: sharded
        leaves psum their partial over the axis that shards them (pad
        elements are zero, so the sum is exact)."""
        total = jnp.float32(0.0)
        for leaf, m in zip(jax.tree.leaves(tree), metas):
            sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
            if m["kind"] == "flat":
                sq = jax.lax.psum(sq, DP_AXIS)
            elif m["kind"] == "dim":
                sq = jax.lax.psum(sq, m["axis"])
            total = total + sq
        return total

    def _z3_step(storage, opt_state, batch):
        from dgl_operator_tpu.obs.comm import register_collective

        metas = _z3_metas()
        params = _z3_materialize(jax.tree.leaves(storage), metas)
        loss_local, grads_raw = jax.value_and_grad(loss_fn)(params,
                                                            batch)
        loss = jax.lax.pmean(loss_local, DP_AXIS)
        # aggregate grad-reduction bill: flat leaves take the
        # reduce-scatter half (padded flat bytes); repl/dim leaves ride
        # a full allreduce, billed at the ring's 2x-payload cost
        gleaves = list(zip(jax.tree.leaves(grads_raw), metas))
        register_collective(
            "grad_psum_scatter", DP_AXIS,
            sum((g.size + (-g.size) % n) * g.dtype.itemsize
                for g, m in gleaves if m["kind"] == "flat"))
        register_collective(
            "grad_pmean", DP_AXIS,
            sum(2 * g.size * g.dtype.itemsize
                for g, m in gleaves if m["kind"] != "flat"))
        gview = jax.tree_util.tree_unflatten(
            _z3["treedef"],
            [_z3_gview(g, m) for g, m in
             zip(jax.tree.leaves(grads_raw), metas)])
        # elementwise optimizers act per element, so updating the
        # storage views IS the replicated update, restricted to the
        # elements each slot owns — and nothing re-materializes full
        # params: the NEXT step's gathers pull the fresh shards
        updates, opt_state = optimizer.update(gview, opt_state,
                                              storage)
        storage = optax.apply_updates(storage, updates)
        if not with_stats:
            return storage, opt_state, loss
        nonfin_local = _quality._nonfinite_count(grads_raw) + (
            ~jnp.isfinite(loss_local)).astype(jnp.int32)
        pn = jnp.sqrt(_z3_sq(storage, metas))
        stats = {
            "grad_norm": jnp.sqrt(_z3_sq(gview, metas)),
            "param_norm": pn,
            "update_ratio": jnp.sqrt(_z3_sq(updates, metas))
            / (pn + 1e-12),
            "nonfinite": jax.lax.psum(nonfin_local, DP_AXIS),
            "part_loss": loss_local.astype(jnp.float32)[None],
            "part_nonfinite": nonfin_local[None],
        }
        return storage, opt_state, loss, stats

    # the model-health stats pytree (obs/quality.py): pure read-only
    # consumers of intermediates the update already computes — the
    # trajectory is bit-identical with_stats on or off
    from dgl_operator_tpu.obs import quality as _quality

    def _ddp_update(params, opt_state, batch):
        """One DDP-equivalent step for a per-slot batch: grad + pmean
        over dp + optimizer update. The single owner of the K=1 and
        scan-body math, so the steps_per_call equivalence can't drift.
        Returns ``(params, opt_state, loss[, stats])``."""
        from dgl_operator_tpu.obs.comm import register_collective

        loss_local, grads_raw = jax.value_and_grad(loss_fn)(params,
                                                            batch)
        loss = jax.lax.pmean(loss_local, DP_AXIS)
        # trace-time ledger record: the grad allreduce moves ~2x the
        # payload on a ring (reduce-scatter + all-gather halves)
        register_collective(
            "grad_pmean", DP_AXIS,
            sum(2 * g.size * g.dtype.itemsize
                for g in jax.tree.leaves(grads_raw)))
        grads = jax.lax.pmean(grads_raw, DP_AXIS)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if not with_stats:
            return params, opt_state, loss
        stats = _quality.dp_slot_stats(loss_local, grads_raw, grads,
                                       updates, params)
        return params, opt_state, loss, stats

    def _shard_step(params, opt_state, batch, extra=None):
        # each slot's block keeps a size-1 leading dp axis; drop it so
        # loss_fn sees the per-partition batch directly (``extra``
        # carries already-per-slot members — the index_carry scalar)
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        if extra:
            batch = {**batch, **extra}
        if per_step_keys:
            static = {k: v for k, v in batch.items()
                      if k not in per_step_keys}
            xs = {k: batch[k] for k in per_step_keys}

            def body(carry, x):
                p, s = carry[0], carry[1]
                return _ddp_update(p, s, {**static, **x}), None

            init = (params, opt_state, jnp.float32(0.0))
            if with_stats:
                init = init + (_quality.zero_stats_like(),)
            carry, _ = jax.lax.scan(body, init, xs)
            return carry
        if not shard_update:
            return _ddp_update(params, opt_state, batch)
        if zero_stage == 3:
            return _z3_step(params, opt_state, batch)
        sel = _selection(params)
        loss_local, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss_local, DP_AXIS)
        from dgl_operator_tpu.obs.comm import register_collective

        gsel = list(zip(jax.tree.leaves(grads), jax.tree.leaves(sel)))
        register_collective(
            "grad_psum_scatter", DP_AXIS,
            sum((g.size + (-g.size) % n) * g.dtype.itemsize
                for g, s in gsel if s))
        register_collective(
            "grad_pmean", DP_AXIS,
            sum(2 * g.size * g.dtype.itemsize
                for g, s in gsel if not s))
        # the trailing all_gather re-materializes each selected param
        register_collective(
            "param_allgather", DP_AXIS,
            sum((g.size + (-g.size) % n) * g.dtype.itemsize
                for g, s in gsel if s))
        # weight-update sharding, per the rules' selection: for a
        # SELECTED param the reduce-scatter half of the allreduce
        # delivers each slot ITS gradient shard (mean); an unselected
        # param keeps the plain pmean'd gradient and replicated math
        gview = jax.tree.map(
            lambda g, s: (jax.lax.psum_scatter(
                _flat_pad(g), DP_AXIS, scatter_dimension=0,
                tiled=True) / n) if s
            else jax.lax.pmean(g, DP_AXIS), grads, sel)
        pview = jax.tree.map(
            lambda p, s: _my_shard(p) if s else p, params, sel)
        # one optimizer.update over the mixed view: elementwise
        # optimizers treat each leaf independently, so sharded and
        # replicated leaves coexist in one state
        updates, opt_state = optimizer.update(gview, opt_state, pview)
        pview = optax.apply_updates(pview, updates)
        # the all-gather half completes the allreduce with UPDATED
        # weights — every slot re-materializes full params
        new_params = jax.tree.map(
            lambda ps, p, s: jax.lax.all_gather(
                ps, DP_AXIS, tiled=True)[: p.size].reshape(p.shape)
            if s else ps, pview, params, sel)
        if not with_stats:
            return new_params, opt_state, loss
        # WUS stats: sharded leaves' partial square-sums psum into the
        # global norm (a few extra scalar collectives; the non-WUS
        # paths stay collective-free)

        def _wus_sq(tree):
            total = jnp.float32(0.0)
            for leaf, s in zip(jax.tree.leaves(tree),
                               jax.tree.leaves(sel)):
                sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                total = total + (jax.lax.psum(sq, DP_AXIS) if s
                                 else sq)
            return total

        nonfin_local = _quality._nonfinite_count(grads) + (
            ~jnp.isfinite(loss_local)).astype(jnp.int32)
        pn = jnp.sqrt(_quality._sq_sum(new_params))
        stats = {
            "grad_norm": jnp.sqrt(_wus_sq(gview)),
            "param_norm": pn,
            "update_ratio": jnp.sqrt(_wus_sq(updates)) / (pn + 1e-12),
            "nonfinite": jax.lax.psum(nonfin_local, DP_AXIS),
            "part_loss": loss_local.astype(jnp.float32)[None],
            "part_nonfinite": nonfin_local[None],
        }
        return new_params, opt_state, loss, stats

    # shard_map specs: params replicated, batch split on dim 0. With
    # WUS the opt-state placement is DERIVED from the params' rule
    # match (parallel/shardrules.py): a moment inherits its param's
    # spec by tree-path suffix, scalar leaves (adam's step count) stay
    # replicated — the generalization of the old all-or-nothing
    # wus_sharded_leaf rule
    def opt_spec_tree(opt_state, params):
        if not shard_update:
            return jax.tree.map(lambda _: P(), opt_state)
        if zero_stage == 3:
            # moments inherit the param's STORAGE placement (flat dp
            # shard / tp block / replicated) by tree-path suffix
            return shardrules.opt_state_specs(opt_state, params,
                                              _storage_spec_tree())
        return shardrules.opt_state_specs(opt_state, params,
                                          _param_specs(params))

    def param_spec_tree():
        """shard_map in/out spec for the params argument: replicated
        full params on the zero_stage=1 paths, the persistent storage
        placement under ZeRO-3."""
        if zero_stage != 3:
            return P()
        return _storage_spec_tree()

    def batch_spec(batch):
        return jax.tree.map(lambda _: P(DP_AXIS), batch)

    def stats_spec():
        # matches quality.dp_slot_stats: per-partition members stack
        # over dp, the derived norms are replicated
        return {"grad_norm": P(), "param_norm": P(),
                "update_ratio": P(), "nonfinite": P(),
                "part_loss": P(DP_AXIS), "part_nonfinite": P(DP_AXIS)}

    if fused_exchange is not None:
        # fused in-program pipeline: consume this batch's staged
        # payload AND issue the next batch's halo collective inside
        # the same program — start before the update's compute graph,
        # done pinned behind the loss (parallel/halo.py owns the
        # barrier), so the a2a runs under the matmul/aggregation work
        from dgl_operator_tpu.parallel.halo import halo_exchange_done

        def _shard_fused(p, s, b, st, neb):
            bsq = jax.tree.map(lambda x: jnp.squeeze(x, axis=0),
                               {**b, **st})
            neb = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), neb)
            handle = fused_exchange(bsq, neb)       # async start
            out = _shard_step(p, s, {**b, **st})
            p, s, loss = out[0], out[1], out[2]
            recv, loss = halo_exchange_done(handle, loss)
            # restore the slot axis: the ring buffer is a dp-sharded
            # batch member, same discipline as the staged stage
            if with_stats:
                return p, s, loss, recv[None], out[3]
            return p, s, loss, recv[None]

        @partial(jax.jit,
                 donate_argnums=(0, 1, 3, 4) if donate else (3, 4))
        def step(params, opt_state, batch, staged, next_ebatch):
            out_specs = (param_spec_tree(),
                         opt_spec_tree(opt_state, params), P(),
                         P(DP_AXIS))
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_fused, mesh=mesh,
                in_specs=(param_spec_tree(),
                          opt_spec_tree(opt_state, params),
                          batch_spec(batch), batch_spec(staged),
                          batch_spec(next_ebatch)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, staged, next_ebatch)
    elif staged_keys:
        # pipelined form: staging buffers arrive as a separate, always-
        # donated argument (see the staged_keys contract above); the
        # shard body sees one merged batch so loss_fn is layout-blind
        @partial(jax.jit,
                 donate_argnums=(0, 1, 3) if donate else (3,))
        def step(params, opt_state, batch, staged):
            out_specs = (param_spec_tree(),
                         opt_spec_tree(opt_state, params), P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                lambda p, s, b, st: _shard_step(p, s, {**b, **st}),
                mesh=mesh,
                in_specs=(param_spec_tree(),
                          opt_spec_tree(opt_state, params),
                          batch_spec(batch), batch_spec(staged)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, staged)
    elif index_carry:
        # device-resident stream form: the step index is a replicated,
        # always-donated device scalar threaded through the call —
        # loss_fn indexes the epoch's staged seed bank with it, so the
        # steady-state dispatch ships NO host payload at all

        def _shard_idx(p, s, b, i):
            out = _shard_step(p, s, b, extra={"step_idx": i})
            if with_stats:
                return out[0], out[1], out[2], i + 1, out[3]
            return (*out, i + 1)

        @partial(jax.jit,
                 donate_argnums=(0, 1, 3) if donate else (3,))
        def step(params, opt_state, batch, idx):
            out_specs = (param_spec_tree(),
                         opt_spec_tree(opt_state, params), P(),
                         P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_idx, mesh=mesh,
                in_specs=(param_spec_tree(),
                          opt_spec_tree(opt_state, params),
                          batch_spec(batch), P()),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, idx)
    else:
        @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
        def step(params, opt_state, batch):
            out_specs = (param_spec_tree(),
                         opt_spec_tree(opt_state, params), P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_step, mesh=mesh,
                in_specs=(param_spec_tree(),
                          opt_spec_tree(opt_state, params),
                          batch_spec(batch)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch)

    # compile/recompile + cost telemetry seam (ISSUE 12, obs/prof.py):
    # every XLA compile of this program is counted and timed
    # (`jit_compiles_total{fn}`), and the program's per-dispatch
    # FLOPs/bytes from `lower().cost_analysis()` feed the MFU/roofline
    # accounting. The wrapper passes `lower` and the attached seams
    # (opt_placement, init_opt_state) through untouched, so the
    # HLO-inspection tests see the same program.
    from dgl_operator_tpu.obs.prof import instrument_jit
    step = instrument_jit(prog_name, step, role="step")

    # the restore path re-places checkpointed host arrays with the
    # exact placement this step trained under (runtime/dist.py)
    step.opt_placement = opt_spec_tree

    step.zero_stage = zero_stage

    if shard_update:
        def _z3_storage_view(x, m):
            """In-body view of a replicated logical param as this
            slot's persistent storage shard (init-time slicing)."""
            if m["kind"] == "flat":
                return _my_shard(x)
            if m["kind"] == "dim":
                d, block = m["dim"], m["pad_to"] // m["msize"]
                if m["pad_to"] != m["shape"][d]:
                    widths = [(0, 0)] * len(m["shape"])
                    widths[d] = (0, m["pad_to"] - m["shape"][d])
                    x = jnp.pad(x, widths)
                lo = jax.lax.axis_index(m["axis"]) * block
                return jax.lax.dynamic_slice_in_dim(x, lo, block,
                                                    axis=d)
            return x

        def _z3_fake_view(x, m):
            """Abstract per-slot storage shape of a logical param."""
            if m["kind"] == "flat":
                size = int(np.prod(m["shape"], dtype=int))
                return jnp.zeros(((size + n - 1) // n,), x.dtype)
            if m["kind"] == "dim":
                shape = tuple(m["pad_to"] // m["msize"]
                              if i == m["dim"] else s
                              for i, s in enumerate(m["shape"]))
                return jnp.zeros(shape, x.dtype)
            return x

        def init_opt_state(params):
            # leaf specs need the SHARDED state's structure before
            # tracing: derive it from abstract shard shapes of the
            # SELECTED params (unselected keep their full shape).
            # ``params`` is the LOGICAL (replicated) tree on every
            # zero stage — under ZeRO-3 this also records the step's
            # storage plan.
            if zero_stage == 3:
                _z3_record(params)
                metas = _z3_metas()

                def as_views(p):
                    return jax.tree_util.tree_unflatten(
                        _z3["treedef"],
                        [_z3_fake_view(x, m) for x, m in
                         zip(jax.tree.leaves(p), metas)])

                shapes = jax.eval_shape(
                    lambda p: optimizer.init(as_views(p)), params)
                out_specs = opt_spec_tree(shapes, params)
                f = jax.jit(shard_map(
                    lambda p: optimizer.init(
                        jax.tree_util.tree_unflatten(
                            _z3["treedef"],
                            [_z3_storage_view(x, m) for x, m in
                             zip(jax.tree.leaves(p), metas)])),
                    mesh=mesh, in_specs=(P(),),
                    out_specs=out_specs, check_vma=False))
                return f(params)
            sel = _selection(params)

            def fake_shards(p):
                return jax.tree.map(
                    lambda x, s: jnp.zeros(
                        ((np.prod(x.shape, dtype=int) + n - 1) // n,),
                        x.dtype) if s else x, p, sel)

            shapes = jax.eval_shape(
                lambda p: optimizer.init(fake_shards(p)), params)
            out_specs = opt_spec_tree(shapes, params)
            f = jax.jit(shard_map(
                lambda p: optimizer.init(jax.tree.map(
                    lambda x, s: _my_shard(x) if s else x, p, sel)),
                mesh=mesh, in_specs=(P(),),
                out_specs=out_specs, check_vma=False))
            return f(params)

        step.init_opt_state = init_opt_state
        step.param_specs = _param_specs

    if zero_stage == 3:
        def _host_value(x):
            if not hasattr(x, "addressable_shards"):
                return np.asarray(x)
            if getattr(x, "is_fully_addressable", True):
                return np.asarray(jax.device_get(x))
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))

        def _pad_storage_host(leaf, m):
            if m["kind"] == "flat":
                return shardrules.pad_flat(leaf, n)
            if m["kind"] == "dim":
                mults = [1] * len(m["shape"])
                mults[m["dim"]] = m["msize"]
                return shardrules.pad_dims(leaf, mults)
            return np.asarray(leaf)

        def shard_params(params):
            """Logical params (replicated device arrays or host) ->
            the placed persistent storage tree. Records the storage
            plan; must run before the first step/restore."""
            _z3_record(params)
            metas = _z3_metas()
            host = [_host_value(x) for x in jax.tree.leaves(params)]
            tree = jax.tree_util.tree_unflatten(
                _z3["treedef"],
                [_pad_storage_host(h, m)
                 for h, m in zip(host, metas)])
            return shardrules.place_by_specs(mesh, tree,
                                             _storage_spec_tree())

        def _logical_params_host(storage):
            metas = _z3_metas()
            return jax.tree_util.tree_unflatten(
                _z3["treedef"],
                [shardrules.unpad_leaf(_host_value(x), m["shape"])
                 for x, m in zip(jax.tree.leaves(storage), metas)])

        def gather_params(storage):
            """Full replicated params from the persistent shards —
            the eval/serving/export face (a host round-trip, fine at
            eval cadence; the hot step never re-materializes)."""
            return replicate(mesh, _logical_params_host(storage))

        def _inherit_meta(path):
            best = None
            for ppath, m in zip(_z3["paths"], _z3_metas()):
                if path == ppath or path.endswith("/" + ppath):
                    if best is None or len(ppath) > len(best[0]):
                        best = (ppath, m)
            return best[1] if best else None

        def logical_state(storage, opt_state=None):
            """Host logical (padding-free) ``(params, opt_state)`` —
            the mesh-shape-invariant checkpoint form: flat shards are
            de-padded and reshaped, TP blocks reassembled and sliced,
            so a checkpoint written here re-places bit-exactly on ANY
            mesh shape via :func:`adopt_state`."""
            lp = _logical_params_host(storage)
            if opt_state is None:
                return lp, None
            paths = [p for p, _ in shardrules.tree_paths(opt_state)]
            leaves, treedef = jax.tree_util.tree_flatten(opt_state)
            out = []
            for path, leaf in zip(paths, leaves):
                h = _host_value(leaf)
                m = _inherit_meta(path)
                # meta kind, NOT leaf size, decides: a small param's
                # moment can be 1 element per slot and still be a
                # dp-sharded flat leaf ("repl" metas de-pad to
                # identity; no-ancestry leaves — adam's count — pass
                # through raw)
                if m is None:
                    out.append(h)
                else:
                    out.append(shardrules.unpad_leaf(h, m["shape"]))
            return lp, jax.tree_util.tree_unflatten(treedef, out)

        def adopt_state(logical_params, logical_opt=None):
            """Re-pad and re-place a LOGICAL checkpoint under THIS
            mesh's storage plan — whatever mesh shape wrote it, the
            flat-shard and block padding are regenerated for this
            mesh's axis sizes (pad elements are zeros on every mesh,
            so the round-trip is bit-exact)."""
            storage = shard_params(logical_params)
            if logical_opt is None:
                return storage, None
            paths = [p for p, _ in shardrules.tree_paths(logical_opt)]
            leaves, treedef = jax.tree_util.tree_flatten(logical_opt)
            padded, specs = [], []
            for path, leaf in zip(paths, leaves):
                m = _inherit_meta(path)
                # mirror of logical_state: the meta decides, never the
                # leaf's size (a 1-element logical moment of a tiny
                # flat-sharded param must re-pad to the storage spec,
                # not silently re-place replicated)
                if m is None:
                    padded.append(np.asarray(leaf))
                    specs.append(P())
                else:
                    padded.append(_pad_storage_host(np.asarray(leaf),
                                                    m))
                    specs.append(m["spec"])
            opt = shardrules.place_by_specs(
                mesh, jax.tree_util.tree_unflatten(treedef, padded),
                jax.tree_util.tree_unflatten(treedef, specs))
            return storage, opt

        step.shard_params = shard_params
        step.gather_params = gather_params
        step.logical_state = logical_state
        step.adopt_state = adopt_state
        step.storage_specs = _storage_spec_tree
    return step


def make_dp_eval_step(metric_fn: Callable, mesh: Mesh):
    """Replicated-params eval over dp-sharded batches; metrics are
    (sum, count) pairs psum'd over the axis so global averages are exact
    even with uneven masking."""

    def _shard_eval(params, batch):
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        s, c = metric_fn(params, batch)
        return jax.lax.psum(s, DP_AXIS), jax.lax.psum(c, DP_AXIS)

    @jax.jit
    def evaluate(params, batch):
        f = shard_map(
            _shard_eval, mesh=mesh,
            in_specs=(P(), jax.tree.map(lambda _: P(DP_AXIS), batch)),
            out_specs=(P(), P()),
            check_vma=False)
        s, c = f(params, batch)
        return s / jnp.maximum(c, 1)

    return evaluate


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device.

    Multi-process (multi-controller SPMD): every process passes the SAME
    host value (same init seed / same checkpoint) and contributes its
    addressable replicas via ``make_array_from_process_local_data`` —
    ``device_put`` cannot target non-addressable devices."""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sh, np.asarray(x)), tree)


def dp_shard(mesh: Mesh, tree):
    """Place a stacked batch pytree with leading dim over dp.

    Single process: leaves carry the FULL leading dp extent. Multi-
    process: each process passes only the rows for ITS mesh slots
    (contiguous block, process order) and the global array is assembled
    across processes (the reference analogue: each worker pod holds only
    its own partition, train_dist.py:270-277)."""
    def put(x):
        spec = P(DP_AXIS, *([None] * (np.ndim(x) - 1)))
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        return jax.make_array_from_process_local_data(sh, np.asarray(x))
    return jax.tree.map(put, tree)
