"""Graph-partitioned data-parallel training step.

The reference's data parallelism: each worker trains on mini-batches
sampled from its own graph partition, dense gradients are allreduced by
PyTorch DDP over gloo per backward bucket
(examples/GraphSAGE_dist/code/train_dist.py:187-192,267-270). The
TPU-native form is one jit'd SPMD program over the ``dp`` mesh axis:
every mesh slot consumes its partition's batch, and the gradient
``psum`` is a single fused ICI collective XLA schedules inside the
backward pass — the role DDP's bucketing plays, without the buckets.

``make_dp_train_step`` builds that program once for any (loss_fn,
optimizer); batches are pytrees whose leaves carry a leading mesh-slot
axis (stacked per-partition batches, see ``stack_batches``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgl_operator_tpu.parallel.mesh import DP_AXIS, shard_map


def stack_batches(batches):
    """Stack per-partition host batches into one pytree with a leading
    dp axis (the host-side analogue of DistDataLoader handing each
    worker its own batch, train_dist.py:177-182)."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def wus_sharded_leaf(x) -> bool:
    """Single owner of the weight-update-sharding placement rule:
    array leaves of the optimizer state shard over dp, scalar leaves
    (adam's step count) stay replicated. Works on concrete arrays and
    ShapeDtypeStructs alike."""
    return len(getattr(x, "shape", ())) > 0


def make_dp_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                       mesh: Mesh, donate: bool = True,
                       shard_update: bool = False,
                       per_step_keys: "tuple | None" = None,
                       staged_keys: "tuple | None" = None):
    """Build the jitted SPMD step.

    loss_fn(params, batch) -> scalar loss for ONE mesh slot's batch.
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where ``batch`` leaves have leading dim == mesh dp size and params
    are replicated.

    ``staged_keys`` is the decoupled-pipeline face (the DistTrainer
    halo prefetch stage, runtime/dist.py): the step's signature becomes
    ``step(params, opt_state, batch, staged)`` where ``staged`` is a
    dict holding exactly those keys (dp-sharded like the batch),
    produced by an upstream jitted stage — and ``staged`` is ALWAYS
    donated, because a staging buffer is consumed by exactly one step
    and donating it is what keeps pipeline HBM flat at the staging
    depth instead of growing a buffer per in-flight batch. The batch
    itself is never donated (it carries step-invariant device-resident
    members like the feature shards). Not composable with
    ``per_step_keys`` (the scan stacks per-step members itself).

    ``per_step_keys`` turns the step into a K-step ``lax.scan`` (the
    DistTrainer face of ``TrainConfig.steps_per_call``): ``batch`` must
    be a dict whose listed keys carry a K axis after the dp one
    (``[P, K, ...]``); every other key is step-invariant (features,
    CSR shards). Each scan iteration runs the full grad + pmean +
    update; the returned loss is the last step's. Collectives inside
    ``lax.scan`` under shard_map are ordinary XLA collectives — same
    program K times, one dispatch. Not composable with
    ``shard_update`` (the WUS reduce-scatter path stays per-dispatch).

    ``shard_update=True`` enables cross-replica weight-update sharding
    (Xu et al., arXiv:2004.13336 — the ZeRO-style dp-redundancy
    elimination, PAPERS.md): gradients are ``psum_scatter``'d so each
    dp slot owns 1/n of every parameter's flattened elements, the
    optimizer (and its ENTIRE state — Adam moments live sharded, 1/n
    per device) updates only that shard, and the fresh shards are
    ``all_gather``'d back into replicated params. Same math as the
    replicated form for any elementwise optimizer — reduce-scatter +
    all-gather IS an allreduce — at 1/n the optimizer-state HBM and
    1/n the update FLOPs per device. Build the sharded state with the
    returned step's ``init_opt_state(params)``.
    """
    if per_step_keys and shard_update:
        raise ValueError("per_step_keys multi-step scan does not "
                         "compose with shard_update")
    if per_step_keys and staged_keys:
        raise ValueError("staged_keys (decoupled staging buffers) does "
                         "not compose with per_step_keys (the K-step "
                         "scan stacks its own per-step members)")
    n = int(mesh.shape[DP_AXIS])

    def _flat_pad(x):
        flat = x.reshape(-1)
        pad = (-flat.size) % n
        return jnp.pad(flat, (0, pad)) if pad else flat

    def _my_shard(x):
        flat = _flat_pad(x)
        k = flat.size // n
        return jax.lax.dynamic_slice(
            flat, (jax.lax.axis_index(DP_AXIS) * k,), (k,))

    def _ddp_update(params, opt_state, batch):
        """One DDP-equivalent step for a per-slot batch: grad + pmean
        over dp + optimizer update. The single owner of the K=1 and
        scan-body math, so the steps_per_call equivalence can't drift."""
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, DP_AXIS)
        grads = jax.lax.pmean(grads, DP_AXIS)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def _shard_step(params, opt_state, batch):
        # each slot's block keeps a size-1 leading dp axis; drop it so
        # loss_fn sees the per-partition batch directly
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        if per_step_keys:
            static = {k: v for k, v in batch.items()
                      if k not in per_step_keys}
            xs = {k: batch[k] for k in per_step_keys}

            def body(carry, x):
                p, s, _ = carry
                return _ddp_update(p, s, {**static, **x}), None

            (params, opt_state, loss), _ = jax.lax.scan(
                body, (params, opt_state,
                       jnp.float32(0.0)), xs)
            return params, opt_state, loss
        if not shard_update:
            return _ddp_update(params, opt_state, batch)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, DP_AXIS)
        # weight-update sharding: the reduce-scatter half of the
        # allreduce delivers each slot ITS gradient shard (mean)
        gshard = jax.tree.map(
            lambda g: jax.lax.psum_scatter(
                _flat_pad(g), DP_AXIS, scatter_dimension=0,
                tiled=True) / n, grads)
        pshard = jax.tree.map(_my_shard, params)
        updates, opt_state = optimizer.update(gshard, opt_state,
                                              pshard)
        pshard = optax.apply_updates(pshard, updates)
        # the all-gather half completes the allreduce with UPDATED
        # weights — every slot re-materializes full params
        params = jax.tree.map(
            lambda ps, p: jax.lax.all_gather(
                ps, DP_AXIS, tiled=True)[: p.size].reshape(p.shape),
            pshard, params)
        return params, opt_state, loss

    # shard_map specs: params replicated, batch split on dim 0. With
    # WUS the opt state is sharded over dp EXCEPT scalar leaves (adam's
    # step count), which stay replicated
    def opt_spec_tree(opt_state):
        if not shard_update:
            return jax.tree.map(lambda _: P(), opt_state)
        return jax.tree.map(
            lambda x: P(DP_AXIS) if wus_sharded_leaf(x) else P(),
            opt_state)

    def batch_spec(batch):
        return jax.tree.map(lambda _: P(DP_AXIS), batch)

    if staged_keys:
        # pipelined form: staging buffers arrive as a separate, always-
        # donated argument (see the staged_keys contract above); the
        # shard body sees one merged batch so loss_fn is layout-blind
        @partial(jax.jit,
                 donate_argnums=(0, 1, 3) if donate else (3,))
        def step(params, opt_state, batch, staged):
            f = shard_map(
                lambda p, s, b, st: _shard_step(p, s, {**b, **st}),
                mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state),
                          batch_spec(batch), batch_spec(staged)),
                out_specs=(P(), opt_spec_tree(opt_state), P()),
                check_vma=False)
            return f(params, opt_state, batch, staged)
    else:
        @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
        def step(params, opt_state, batch):
            f = shard_map(
                _shard_step, mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state),
                          batch_spec(batch)),
                out_specs=(P(), opt_spec_tree(opt_state), P()),
                check_vma=False)
            return f(params, opt_state, batch)

    if shard_update:
        def init_opt_state(params):
            # leaf specs need the SHARDED state's structure before
            # tracing: derive it from abstract shard shapes
            def fake_shards(p):
                return jax.tree.map(
                    lambda x: jnp.zeros(
                        ((np.prod(x.shape, dtype=int) + n - 1) // n,),
                        x.dtype), p)

            shapes = jax.eval_shape(
                lambda p: optimizer.init(fake_shards(p)), params)
            out_specs = jax.tree.map(
                lambda s: P(DP_AXIS) if wus_sharded_leaf(s) else P(),
                shapes)
            f = jax.jit(shard_map(
                lambda p: optimizer.init(jax.tree.map(_my_shard, p)),
                mesh=mesh, in_specs=(P(),),
                out_specs=out_specs, check_vma=False))
            return f(params)

        step.init_opt_state = init_opt_state
    return step


def make_dp_eval_step(metric_fn: Callable, mesh: Mesh):
    """Replicated-params eval over dp-sharded batches; metrics are
    (sum, count) pairs psum'd over the axis so global averages are exact
    even with uneven masking."""

    def _shard_eval(params, batch):
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        s, c = metric_fn(params, batch)
        return jax.lax.psum(s, DP_AXIS), jax.lax.psum(c, DP_AXIS)

    @jax.jit
    def evaluate(params, batch):
        f = shard_map(
            _shard_eval, mesh=mesh,
            in_specs=(P(), jax.tree.map(lambda _: P(DP_AXIS), batch)),
            out_specs=(P(), P()),
            check_vma=False)
        s, c = f(params, batch)
        return s / jnp.maximum(c, 1)

    return evaluate


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device.

    Multi-process (multi-controller SPMD): every process passes the SAME
    host value (same init seed / same checkpoint) and contributes its
    addressable replicas via ``make_array_from_process_local_data`` —
    ``device_put`` cannot target non-addressable devices."""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sh, np.asarray(x)), tree)


def dp_shard(mesh: Mesh, tree):
    """Place a stacked batch pytree with leading dim over dp.

    Single process: leaves carry the FULL leading dp extent. Multi-
    process: each process passes only the rows for ITS mesh slots
    (contiguous block, process order) and the global array is assembled
    across processes (the reference analogue: each worker pod holds only
    its own partition, train_dist.py:270-277)."""
    def put(x):
        spec = P(DP_AXIS, *([None] * (np.ndim(x) - 1)))
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        return jax.make_array_from_process_local_data(sh, np.asarray(x))
    return jax.tree.map(put, tree)
