"""Graph-partitioned data-parallel training step.

The reference's data parallelism: each worker trains on mini-batches
sampled from its own graph partition, dense gradients are allreduced by
PyTorch DDP over gloo per backward bucket
(examples/GraphSAGE_dist/code/train_dist.py:187-192,267-270). The
TPU-native form is one jit'd SPMD program over the ``dp`` mesh axis:
every mesh slot consumes its partition's batch, and the gradient
``psum`` is a single fused ICI collective XLA schedules inside the
backward pass — the role DDP's bucketing plays, without the buckets.

``make_dp_train_step`` builds that program once for any (loss_fn,
optimizer); batches are pytrees whose leaves carry a leading mesh-slot
axis (stacked per-partition batches, see ``stack_batches``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgl_operator_tpu.parallel.mesh import DP_AXIS, shard_map
from dgl_operator_tpu.parallel import shardrules


def stack_batches(batches):
    """Stack per-partition host batches into one pytree with a leading
    dp axis (the host-side analogue of DistDataLoader handing each
    worker its own batch, train_dist.py:177-182)."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def wus_sharded_leaf(x) -> bool:
    """Legacy all-params placement rule (kept as a public seam): array
    leaves of the optimizer state shard over dp, scalar leaves (adam's
    step count) stay replicated. The general form — per-param rules,
    moments inheriting their param's spec by tree path — lives in
    ``parallel.shardrules`` and is what this module derives placement
    from now."""
    return len(getattr(x, "shape", ())) > 0


def _validate_dp_rules(rules):
    """Rules for the dense DP path may only target the dp axis (a rule
    naming any other axis would be tensor parallelism, which this step
    does not implement) — loud, not silently replicated."""
    for pat, spec in rules:
        ps = shardrules.to_pspec(spec)
        for entry in ps:
            for ax in ((entry,) if isinstance(entry, str)
                       else (entry or ())):
                if ax != DP_AXIS:
                    raise ValueError(
                        f"shard_rules entry {pat!r} names axis {ax!r}; "
                        f"the DP train step only supports {DP_AXIS!r} "
                        "(ZeRO-style weight-update sharding) or None "
                        "(replicated)")


def make_dp_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                       mesh: Mesh, donate: bool = True,
                       shard_update: bool = False,
                       shard_rules: "tuple | None" = None,
                       per_step_keys: "tuple | None" = None,
                       staged_keys: "tuple | None" = None,
                       fused_exchange: "Callable | None" = None,
                       index_carry: bool = False,
                       with_stats: bool = False,
                       prog_name: str = "dp_train_step"):
    """Build the jitted SPMD step.

    loss_fn(params, batch) -> scalar loss for ONE mesh slot's batch.
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where ``batch`` leaves have leading dim == mesh dp size and params
    are replicated.

    ``staged_keys`` is the decoupled-pipeline face (the DistTrainer
    halo prefetch stage, runtime/dist.py): the step's signature becomes
    ``step(params, opt_state, batch, staged)`` where ``staged`` is a
    dict holding exactly those keys (dp-sharded like the batch),
    produced by an upstream jitted stage — and ``staged`` is ALWAYS
    donated, because a staging buffer is consumed by exactly one step
    and donating it is what keeps pipeline HBM flat at the staging
    depth instead of growing a buffer per in-flight batch. The batch
    itself is never donated (it carries step-invariant device-resident
    members like the feature shards). Not composable with
    ``per_step_keys`` (the scan stacks per-step members itself).

    ``per_step_keys`` turns the step into a K-step ``lax.scan`` (the
    DistTrainer face of ``TrainConfig.steps_per_call``): ``batch`` must
    be a dict whose listed keys carry a K axis after the dp one
    (``[P, K, ...]``); every other key is step-invariant (features,
    CSR shards). Each scan iteration runs the full grad + pmean +
    update; the returned loss is the last step's. Collectives inside
    ``lax.scan`` under shard_map are ordinary XLA collectives — same
    program K times, one dispatch. Not composable with
    ``shard_update`` (the WUS reduce-scatter path stays per-dispatch).

    ``shard_update=True`` enables cross-replica weight-update sharding
    (Xu et al., arXiv:2004.13336 — the ZeRO-style dp-redundancy
    elimination, PAPERS.md): gradients are ``psum_scatter``'d so each
    dp slot owns 1/n of every parameter's flattened elements, the
    optimizer (and its ENTIRE state — Adam moments live sharded, 1/n
    per device) updates only that shard, and the fresh shards are
    ``all_gather``'d back into replicated params. Same math as the
    replicated form for any elementwise optimizer — reduce-scatter +
    all-gather IS an allreduce — at 1/n the optimizer-state HBM and
    1/n the update FLOPs per device. Build the sharded state with the
    returned step's ``init_opt_state(params)``.

    ``fused_exchange`` is the in-program async-collective face (the
    DistTrainer fused pipeline, ``TrainConfig.pipeline_mode="fused"``):
    requires ``staged_keys``, and the step's signature becomes
    ``step(params, opt_state, batch, staged, next_ebatch) ->
    (params, opt_state, loss, next_recv)``. Inside the shard body the
    NEXT batch's halo collective is ISSUED first
    (``fused_exchange(batch, next_ebatch)`` — the async start), the
    DDP update runs on this batch's already-staged payload, and the
    in-flight handle is pinned behind the loss through
    ``parallel.halo.halo_exchange_done`` (one optimization barrier) so
    XLA cannot sink the done next to the start — the collective and
    the compute stay independent subgraphs joined only at the outputs,
    which is what lets the scheduler run the a2a under the MXU work.
    ``next_ebatch`` is ALWAYS donated (one batch's request table, dead
    after the start), like ``staged``; the returned ``next_recv`` is
    the staging-ring buffer the step at t+K consumes.

    ``index_carry`` is the device-resident stream face (the device
    sampler's zero-host-sync steady state): the signature becomes
    ``step(params, opt_state, batch, idx) -> (params, opt_state,
    loss, idx + 1)`` where ``idx`` is a replicated, ALWAYS-donated
    device scalar the loop threads back in. ``loss_fn`` sees it as
    ``batch["step_idx"]`` and indexes the epoch's device-resident
    seed bank with it — no per-step host staging at all. Not
    composable with ``per_step_keys`` / ``staged_keys`` (the scan and
    the staging ring carry their own per-step members).

    ``with_stats`` is the model-health face (ISSUE 15, obs/quality.py):
    the step additionally returns a small jit-computed stats pytree —
    per-partition loss and non-finite gradient counts (``[P]``, the
    partition attribution of the numerics sentry), plus replicated
    global grad/param norms and the update ratio. Appended as the LAST
    return value of every signature variant. The stats are pure
    read-only consumers of intermediates the update already computes
    (loss before the pmean, the pmean'd grads, the updates, the fresh
    params), so the parameter trajectory is BIT-IDENTICAL to
    ``with_stats=False`` and — on the non-WUS paths — no additional
    collective is emitted (per-partition members ride the dp
    out-spec). The WUS path psums its sharded-leaf partial norms (a
    few scalars per step). Pinned by tests/test_quality.py.

    ``shard_rules`` is the general, rule-driven form of the same mode
    (parallel/shardrules.py): ordered ``(regex, spec)`` pairs matched
    first-match-wins against each param's '/'-joined tree path. A
    param whose spec names the dp axis gets the weight-update-sharding
    treatment above (its optimizer state lives 1/n per device); a
    replicated spec keeps the plain pmean update. ``shard_update=True``
    is exactly ``shard_rules=(('.*', 'dp'),)``. Scalar params and
    scalar state leaves (Adam's count) always stay replicated. The
    placement the step derives for any state is exposed as
    ``step.opt_placement(opt_state, params)`` — the checkpoint restore
    path re-places restored host arrays with it.
    """
    if shard_update and shard_rules is not None:
        raise ValueError("pass either shard_update=True (all params) "
                         "or shard_rules (per-param), not both")
    if shard_update:
        shard_rules = ((".*", DP_AXIS),)
    if shard_rules is not None:
        _validate_dp_rules(shard_rules)
        shard_update = True   # rules engage the WUS code path below
    if per_step_keys and shard_update:
        raise ValueError("per_step_keys multi-step scan does not "
                         "compose with shard_update")
    if per_step_keys and staged_keys:
        raise ValueError("staged_keys (decoupled staging buffers) does "
                         "not compose with per_step_keys (the K-step "
                         "scan stacks its own per-step members)")
    if fused_exchange is not None and not staged_keys:
        raise ValueError("fused_exchange requires staged_keys (the "
                         "fused step consumes this batch's staged "
                         "payload while issuing the next batch's "
                         "exchange)")
    if index_carry and (per_step_keys or staged_keys):
        raise ValueError("index_carry (device-resident stream index) "
                         "does not compose with per_step_keys or "
                         "staged_keys (the scan and the staging ring "
                         "carry their own per-step members)")
    n = int(mesh.shape[DP_AXIS])

    def _flat_pad(x):
        flat = x.reshape(-1)
        pad = (-flat.size) % n
        return jnp.pad(flat, (0, pad)) if pad else flat

    def _my_shard(x):
        flat = _flat_pad(x)
        k = flat.size // n
        return jax.lax.dynamic_slice(
            flat, (jax.lax.axis_index(DP_AXIS) * k,), (k,))

    def _selection(params):
        """Per-param WUS selection from the rules: True where the
        matched spec shards over dp (pytree of Python bools — static,
        derivable from tracers)."""
        specs = shardrules.match_partition_rules(shard_rules, params)
        return jax.tree.map(lambda s: DP_AXIS in jax.tree.leaves(
            tuple(s)), specs)

    def _param_specs(params):
        """Accounting/placement view of the params under the rules
        (scalars replicated, per shardrules contract)."""
        return shardrules.match_partition_rules(shard_rules, params)

    # the model-health stats pytree (obs/quality.py): pure read-only
    # consumers of intermediates the update already computes — the
    # trajectory is bit-identical with_stats on or off
    from dgl_operator_tpu.obs import quality as _quality

    def _ddp_update(params, opt_state, batch):
        """One DDP-equivalent step for a per-slot batch: grad + pmean
        over dp + optimizer update. The single owner of the K=1 and
        scan-body math, so the steps_per_call equivalence can't drift.
        Returns ``(params, opt_state, loss[, stats])``."""
        loss_local, grads_raw = jax.value_and_grad(loss_fn)(params,
                                                            batch)
        loss = jax.lax.pmean(loss_local, DP_AXIS)
        grads = jax.lax.pmean(grads_raw, DP_AXIS)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if not with_stats:
            return params, opt_state, loss
        stats = _quality.dp_slot_stats(loss_local, grads_raw, grads,
                                       updates, params)
        return params, opt_state, loss, stats

    def _shard_step(params, opt_state, batch, extra=None):
        # each slot's block keeps a size-1 leading dp axis; drop it so
        # loss_fn sees the per-partition batch directly (``extra``
        # carries already-per-slot members — the index_carry scalar)
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        if extra:
            batch = {**batch, **extra}
        if per_step_keys:
            static = {k: v for k, v in batch.items()
                      if k not in per_step_keys}
            xs = {k: batch[k] for k in per_step_keys}

            def body(carry, x):
                p, s = carry[0], carry[1]
                return _ddp_update(p, s, {**static, **x}), None

            init = (params, opt_state, jnp.float32(0.0))
            if with_stats:
                init = init + (_quality.zero_stats_like(),)
            carry, _ = jax.lax.scan(body, init, xs)
            return carry
        if not shard_update:
            return _ddp_update(params, opt_state, batch)
        sel = _selection(params)
        loss_local, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss_local, DP_AXIS)
        # weight-update sharding, per the rules' selection: for a
        # SELECTED param the reduce-scatter half of the allreduce
        # delivers each slot ITS gradient shard (mean); an unselected
        # param keeps the plain pmean'd gradient and replicated math
        gview = jax.tree.map(
            lambda g, s: (jax.lax.psum_scatter(
                _flat_pad(g), DP_AXIS, scatter_dimension=0,
                tiled=True) / n) if s
            else jax.lax.pmean(g, DP_AXIS), grads, sel)
        pview = jax.tree.map(
            lambda p, s: _my_shard(p) if s else p, params, sel)
        # one optimizer.update over the mixed view: elementwise
        # optimizers treat each leaf independently, so sharded and
        # replicated leaves coexist in one state
        updates, opt_state = optimizer.update(gview, opt_state, pview)
        pview = optax.apply_updates(pview, updates)
        # the all-gather half completes the allreduce with UPDATED
        # weights — every slot re-materializes full params
        new_params = jax.tree.map(
            lambda ps, p, s: jax.lax.all_gather(
                ps, DP_AXIS, tiled=True)[: p.size].reshape(p.shape)
            if s else ps, pview, params, sel)
        if not with_stats:
            return new_params, opt_state, loss
        # WUS stats: sharded leaves' partial square-sums psum into the
        # global norm (a few extra scalar collectives; the non-WUS
        # paths stay collective-free)

        def _wus_sq(tree):
            total = jnp.float32(0.0)
            for leaf, s in zip(jax.tree.leaves(tree),
                               jax.tree.leaves(sel)):
                sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                total = total + (jax.lax.psum(sq, DP_AXIS) if s
                                 else sq)
            return total

        nonfin_local = _quality._nonfinite_count(grads) + (
            ~jnp.isfinite(loss_local)).astype(jnp.int32)
        pn = jnp.sqrt(_quality._sq_sum(new_params))
        stats = {
            "grad_norm": jnp.sqrt(_wus_sq(gview)),
            "param_norm": pn,
            "update_ratio": jnp.sqrt(_wus_sq(updates)) / (pn + 1e-12),
            "nonfinite": jax.lax.psum(nonfin_local, DP_AXIS),
            "part_loss": loss_local.astype(jnp.float32)[None],
            "part_nonfinite": nonfin_local[None],
        }
        return new_params, opt_state, loss, stats

    # shard_map specs: params replicated, batch split on dim 0. With
    # WUS the opt-state placement is DERIVED from the params' rule
    # match (parallel/shardrules.py): a moment inherits its param's
    # spec by tree-path suffix, scalar leaves (adam's step count) stay
    # replicated — the generalization of the old all-or-nothing
    # wus_sharded_leaf rule
    def opt_spec_tree(opt_state, params):
        if not shard_update:
            return jax.tree.map(lambda _: P(), opt_state)
        return shardrules.opt_state_specs(opt_state, params,
                                          _param_specs(params))

    def batch_spec(batch):
        return jax.tree.map(lambda _: P(DP_AXIS), batch)

    def stats_spec():
        # matches quality.dp_slot_stats: per-partition members stack
        # over dp, the derived norms are replicated
        return {"grad_norm": P(), "param_norm": P(),
                "update_ratio": P(), "nonfinite": P(),
                "part_loss": P(DP_AXIS), "part_nonfinite": P(DP_AXIS)}

    if fused_exchange is not None:
        # fused in-program pipeline: consume this batch's staged
        # payload AND issue the next batch's halo collective inside
        # the same program — start before the update's compute graph,
        # done pinned behind the loss (parallel/halo.py owns the
        # barrier), so the a2a runs under the matmul/aggregation work
        from dgl_operator_tpu.parallel.halo import halo_exchange_done

        def _shard_fused(p, s, b, st, neb):
            bsq = jax.tree.map(lambda x: jnp.squeeze(x, axis=0),
                               {**b, **st})
            neb = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), neb)
            handle = fused_exchange(bsq, neb)       # async start
            out = _shard_step(p, s, {**b, **st})
            p, s, loss = out[0], out[1], out[2]
            recv, loss = halo_exchange_done(handle, loss)
            # restore the slot axis: the ring buffer is a dp-sharded
            # batch member, same discipline as the staged stage
            if with_stats:
                return p, s, loss, recv[None], out[3]
            return p, s, loss, recv[None]

        @partial(jax.jit,
                 donate_argnums=(0, 1, 3, 4) if donate else (3, 4))
        def step(params, opt_state, batch, staged, next_ebatch):
            out_specs = (P(), opt_spec_tree(opt_state, params), P(),
                         P(DP_AXIS))
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_fused, mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state, params),
                          batch_spec(batch), batch_spec(staged),
                          batch_spec(next_ebatch)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, staged, next_ebatch)
    elif staged_keys:
        # pipelined form: staging buffers arrive as a separate, always-
        # donated argument (see the staged_keys contract above); the
        # shard body sees one merged batch so loss_fn is layout-blind
        @partial(jax.jit,
                 donate_argnums=(0, 1, 3) if donate else (3,))
        def step(params, opt_state, batch, staged):
            out_specs = (P(), opt_spec_tree(opt_state, params), P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                lambda p, s, b, st: _shard_step(p, s, {**b, **st}),
                mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state, params),
                          batch_spec(batch), batch_spec(staged)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, staged)
    elif index_carry:
        # device-resident stream form: the step index is a replicated,
        # always-donated device scalar threaded through the call —
        # loss_fn indexes the epoch's staged seed bank with it, so the
        # steady-state dispatch ships NO host payload at all

        def _shard_idx(p, s, b, i):
            out = _shard_step(p, s, b, extra={"step_idx": i})
            if with_stats:
                return out[0], out[1], out[2], i + 1, out[3]
            return (*out, i + 1)

        @partial(jax.jit,
                 donate_argnums=(0, 1, 3) if donate else (3,))
        def step(params, opt_state, batch, idx):
            out_specs = (P(), opt_spec_tree(opt_state, params), P(),
                         P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_idx, mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state, params),
                          batch_spec(batch), P()),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch, idx)
    else:
        @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
        def step(params, opt_state, batch):
            out_specs = (P(), opt_spec_tree(opt_state, params), P())
            if with_stats:
                out_specs = out_specs + (stats_spec(),)
            f = shard_map(
                _shard_step, mesh=mesh,
                in_specs=(P(), opt_spec_tree(opt_state, params),
                          batch_spec(batch)),
                out_specs=out_specs,
                check_vma=False)
            return f(params, opt_state, batch)

    # compile/recompile + cost telemetry seam (ISSUE 12, obs/prof.py):
    # every XLA compile of this program is counted and timed
    # (`jit_compiles_total{fn}`), and the program's per-dispatch
    # FLOPs/bytes from `lower().cost_analysis()` feed the MFU/roofline
    # accounting. The wrapper passes `lower` and the attached seams
    # (opt_placement, init_opt_state) through untouched, so the
    # HLO-inspection tests see the same program.
    from dgl_operator_tpu.obs.prof import instrument_jit
    step = instrument_jit(prog_name, step, role="step")

    # the restore path re-places checkpointed host arrays with the
    # exact placement this step trained under (runtime/dist.py)
    step.opt_placement = opt_spec_tree

    if shard_update:
        def init_opt_state(params):
            # leaf specs need the SHARDED state's structure before
            # tracing: derive it from abstract shard shapes of the
            # SELECTED params (unselected keep their full shape)
            sel = _selection(params)

            def fake_shards(p):
                return jax.tree.map(
                    lambda x, s: jnp.zeros(
                        ((np.prod(x.shape, dtype=int) + n - 1) // n,),
                        x.dtype) if s else x, p, sel)

            shapes = jax.eval_shape(
                lambda p: optimizer.init(fake_shards(p)), params)
            out_specs = opt_spec_tree(shapes, params)
            f = jax.jit(shard_map(
                lambda p: optimizer.init(jax.tree.map(
                    lambda x, s: _my_shard(x) if s else x, p, sel)),
                mesh=mesh, in_specs=(P(),),
                out_specs=out_specs, check_vma=False))
            return f(params)

        step.init_opt_state = init_opt_state
        step.param_specs = _param_specs
    return step


def make_dp_eval_step(metric_fn: Callable, mesh: Mesh):
    """Replicated-params eval over dp-sharded batches; metrics are
    (sum, count) pairs psum'd over the axis so global averages are exact
    even with uneven masking."""

    def _shard_eval(params, batch):
        batch = jax.tree.map(lambda x: jnp.squeeze(x, axis=0), batch)
        s, c = metric_fn(params, batch)
        return jax.lax.psum(s, DP_AXIS), jax.lax.psum(c, DP_AXIS)

    @jax.jit
    def evaluate(params, batch):
        f = shard_map(
            _shard_eval, mesh=mesh,
            in_specs=(P(), jax.tree.map(lambda _: P(DP_AXIS), batch)),
            out_specs=(P(), P()),
            check_vma=False)
        s, c = f(params, batch)
        return s / jnp.maximum(c, 1)

    return evaluate


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device.

    Multi-process (multi-controller SPMD): every process passes the SAME
    host value (same init seed / same checkpoint) and contributes its
    addressable replicas via ``make_array_from_process_local_data`` —
    ``device_put`` cannot target non-addressable devices."""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sh, np.asarray(x)), tree)


def dp_shard(mesh: Mesh, tree):
    """Place a stacked batch pytree with leading dim over dp.

    Single process: leaves carry the FULL leading dp extent. Multi-
    process: each process passes only the rows for ITS mesh slots
    (contiguous block, process order) and the global array is assembled
    across processes (the reference analogue: each worker pod holds only
    its own partition, train_dist.py:270-277)."""
    def put(x):
        spec = P(DP_AXIS, *([None] * (np.ndim(x) - 1)))
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        return jax.make_array_from_process_local_data(sh, np.asarray(x))
    return jax.tree.map(put, tree)
