"""Device-mesh construction — the TPU replacement for process groups.

The reference's distribution fabric is a gloo process group created per
trainer (examples/GraphSAGE_dist/code/train_dist.py:269) plus DGL's
socket RPC between servers and clients. On TPU the single equivalent
object is a ``jax.sharding.Mesh`` over ICI/DCN: collectives are inserted
by XLA from sharding annotations, not hand-coded sends.

Axis convention
---------------
``dp``     graph-partition data parallelism (one partition per mesh slot
           — the role of a reference *worker pod*, train_dist.py:270-277)
``mp``     sharded-embedding model parallelism (the role of the KVStore
           server group, examples/DGL-KE/hotfix/dis_kvstore.py)

A 1-D mesh uses the same physical devices for both roles (every chip
holds a partition and an embedding shard), matching the reference's
co-located server+trainer topology (launch.py:110-152).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
MP_AXIS = "mp"


def make_mesh(num_dp: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D data-parallel mesh over the given (default: all) devices.

    ``num_dp`` trims the device list — e.g. a 2-partition job on an
    8-chip host uses 2 mesh slots, mirroring ``--num-partitions 2``
    jobs in the reference (examples/v1alpha1/GraphSAGE_dist.yaml).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_dp is not None:
        if num_dp > len(devices):
            raise ValueError(f"num_dp={num_dp} > {len(devices)} devices")
        devices = devices[:num_dp]
    return Mesh(np.asarray(devices), (DP_AXIS,))


def make_mesh_2d(num_dp: int, num_mp: int,
                 devices: Optional[Sequence] = None) -> Mesh:
    """dp x mp mesh for jobs that shard embeddings across a sub-axis.

    Lay dp outermost so embedding all-to-alls ride the contiguous inner
    (ICI-adjacent) axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_dp * num_mp
    if need > len(devices):
        raise ValueError(f"mesh {num_dp}x{num_mp} > {len(devices)} devices")
    arr = np.asarray(devices[:need]).reshape(num_dp, num_mp)
    return Mesh(arr, (DP_AXIS, MP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Leading axis split over dp, rest replicated."""
    return NamedSharding(mesh, P(DP_AXIS, *([None] * (ndim - 1))))


def shard_leading(mesh: Mesh, x, axis: str = DP_AXIS):
    """Place a host array with its leading dim split over ``axis``."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def axis_size(mesh: Mesh, axis: str = DP_AXIS) -> int:
    return int(mesh.shape[axis])


def local_dp_rank_slices(mesh: Mesh, n: int) -> Tuple[slice, ...]:
    """Per-rank equal slices of range(n) (drop remainder), used to carve
    host batches for each mesh slot."""
    k = axis_size(mesh)
    per = n // k
    return tuple(slice(i * per, (i + 1) * per) for i in range(k))
