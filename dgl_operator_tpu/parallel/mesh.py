"""Device-mesh construction — the TPU replacement for process groups.

The reference's distribution fabric is a gloo process group created per
trainer (examples/GraphSAGE_dist/code/train_dist.py:269) plus DGL's
socket RPC between servers and clients. On TPU the single equivalent
object is a ``jax.sharding.Mesh`` over ICI/DCN: collectives are inserted
by XLA from sharding annotations, not hand-coded sends.

Axis convention
---------------
``dp``     graph-partition data parallelism (one partition per mesh slot
           — the role of a reference *worker pod*, train_dist.py:270-277)
``mp``     sharded-embedding model parallelism (the role of the KVStore
           server group, examples/DGL-KE/hotfix/dis_kvstore.py)

A 1-D mesh uses the same physical devices for both roles (every chip
holds a partition and an embedding shard), matching the reference's
co-located server+trainer topology (launch.py:110-152).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
MP_AXIS = "mp"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions — the single owner of the
    version seam. Newer jax exposes it top-level with the ``check_vma``
    knob; 0.4.x only has ``jax.experimental.shard_map`` whose equivalent
    flag is ``check_rep``. Every shard_map in this package binds through
    here so a jax upgrade (or downgrade in a hermetic image) is a
    one-line event, not a grep."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      check_rep=(True if check_vma is None
                                 else bool(check_vma)))


def body_axis_size(axis: str) -> int:
    """Static mesh-axis size from inside a shard_map/collective body —
    ``jax.lax.axis_size`` where it exists, the axis-frame lookup on
    0.4.x. Same version seam as :func:`shard_map`."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    from jax._src.core import axis_frame
    return axis_frame(axis)   # 0.4.x: returns the size directly


def make_mesh(num_dp: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D data-parallel mesh over the given (default: all) devices.

    ``num_dp`` trims the device list — e.g. a 2-partition job on an
    8-chip host uses 2 mesh slots, mirroring ``--num-partitions 2``
    jobs in the reference (examples/v1alpha1/GraphSAGE_dist.yaml).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_dp is not None:
        if num_dp > len(devices):
            raise ValueError(f"num_dp={num_dp} > {len(devices)} devices")
        devices = devices[:num_dp]
    return Mesh(np.asarray(devices), (DP_AXIS,))


def make_mesh_2d(num_dp: int, num_mp: int,
                 devices: Optional[Sequence] = None) -> Mesh:
    """dp x mp mesh for jobs that shard embeddings across a sub-axis.

    Lay dp outermost so embedding all-to-alls ride the contiguous inner
    (ICI-adjacent) axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_dp * num_mp
    if need > len(devices):
        raise ValueError(f"mesh {num_dp}x{num_mp} > {len(devices)} devices")
    arr = np.asarray(devices[:need]).reshape(num_dp, num_mp)
    return Mesh(arr, (DP_AXIS, MP_AXIS))


def make_train_mesh(num_dp: int, tp_axis_size: int = 1,
                    devices: Optional[Sequence] = None) -> Mesh:
    """The training-plane mesh for a ``(zero_stage, tp_axis_size)``
    config in one call: 1-D dp mesh when tensor parallelism is off,
    the dp-outermost ``dp x mp`` mesh when ``tp_axis_size > 1`` (the
    shape ``TrainConfig.tp_axis_size`` validates against). Keeping the
    1-D shape for tp=1 matters: dp-only programs stay byte-identical
    to pre-TP meshes, so sharding a model is opt-in per job, not a
    global topology change."""
    if int(tp_axis_size) <= 1:
        return make_mesh(num_dp=num_dp, devices=devices)
    return make_mesh_2d(num_dp, int(tp_axis_size), devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Leading axis split over dp, rest replicated."""
    return NamedSharding(mesh, P(DP_AXIS, *([None] * (ndim - 1))))


def shard_leading(mesh: Mesh, x, axis: str = DP_AXIS):
    """Place a host array with its leading dim split over ``axis``."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def axis_size(mesh: Mesh, axis: str = DP_AXIS) -> int:
    return int(mesh.shape[axis])


def local_dp_rank_slices(mesh: Mesh, n: int) -> Tuple[slice, ...]:
    """Per-rank equal slices of range(n) (drop remainder), used to carve
    host batches for each mesh slot."""
    k = axis_size(mesh)
    per = n // k
    return tuple(slice(i * per, (i + 1) * per) for i in range(k))
