"""Sharded embedding tables with collective lookup/update — the
parameter-server replacement.

The reference stores entity/relation embeddings in a KVStore: tables
sharded by machine, clients ``pull`` rows before scoring and ``push``
gradients back, and the *server* applies row-sparse Adagrad
(examples/DGL-KE/hotfix/dis_kvstore.py:757-902 push/pull;
kvserver.py:41-57 server-side sparse Adagrad). That design exists
because GPUs + Ethernet make remote sparse access expensive and
asynchronous.

On TPU the same capability is a deterministic collective pair inside the
jit program (SURVEY.md §2 "TPU-native equivalent"):

- **pull** == ``all_gather`` the requested ids over the shard axis; every
  shard gathers the rows it owns (one masked local take); a
  ``psum_scatter`` then returns each requester exactly its rows. Both
  collectives ride ICI and XLA overlaps them with compute.
- **push** == ``all_gather`` (ids, grads); every shard segment-sums the
  gradient rows it owns (duplicate ids accumulate, matching KVStore's
  additive push) and applies **row-sparse Adagrad** locally — the exact
  owner-side update semantics of kvserver.py:41-57, minus the RPC.

Everything is static-shape: a lookup of B ids costs the same whether
they hit one shard or all — there is no load-balance pathology to
tune around (the reference's random-server pick, dis_kvstore.py:795-800,
exists to spread that load; XLA's SPMD makes it moot).

Tables are padded to a multiple of the shard count; id -1 is a valid
"no-op" slot pointing at the table's spare padding row.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgl_operator_tpu.parallel.mesh import DP_AXIS, shard_map


@dataclasses.dataclass
class ShardedTableSpec:
    """Static metadata for one sharded table."""

    num_rows: int          # logical rows (un-padded)
    dim: int
    num_shards: int
    axis: str = DP_AXIS

    @property
    def rows_per_shard(self) -> int:
        return -(-self.num_rows // self.num_shards)  # ceil

    @property
    def padded_rows(self) -> int:
        return self.rows_per_shard * self.num_shards


def place_host_array(mesh: Mesh, host, pspec) -> jax.Array:
    """Place a host array every process holds in FULL (same seed / same
    checkpoint) under ``pspec``. Single-process: device_put. Multi-
    controller: each process contributes only its addressable shards —
    ``device_put`` cannot target non-addressable devices. Single owner
    of the staging branch (used by init_table and DistKGETrainer)."""
    sh = NamedSharding(mesh, pspec)
    if jax.process_count() == 1:
        return jax.device_put(host, sh)
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sh, lambda idx: host[idx])


def init_table(spec: ShardedTableSpec, key, scale: float = 1.0,
               mesh: Optional[Mesh] = None) -> jax.Array:
    """Uniform(-scale, scale) init (DGL-KE's emb_init convention),
    padded, and — when a mesh is given — placed shard-by-shard (every
    process derives the same host table from the shared key).

    Values are drawn for the LOGICAL rows and the padding rows are
    zero: the draw must not depend on ``num_shards`` (padding does), or
    the same (key, num_rows) would initialize differently on different
    mesh shapes and cross-mesh trajectory parity breaks."""
    tab = jax.random.uniform(key, (spec.num_rows, spec.dim),
                             jnp.float32, -scale, scale)
    tab = jnp.pad(tab, ((0, spec.padded_rows - spec.num_rows), (0, 0)))
    if mesh is not None:
        return place_host_array(mesh, tab, P(spec.axis))
    return tab


def _owner_and_local(ids, spec: ShardedTableSpec):
    """Row layout is blocked: shard s owns [s*rps, (s+1)*rps)."""
    rps = spec.rows_per_shard
    return ids // rps, ids % rps


def sharded_lookup(table, ids, spec: ShardedTableSpec):
    """Collective pull. Runs *inside* shard_map over ``spec.axis``.

    table : [rows_per_shard, D] local shard.
    ids   : [B] global row ids for THIS mesh slot (-1 = null row).
    returns [B, D].
    """
    from dgl_operator_tpu.obs.comm import register_collective

    ax = spec.axis
    nshard = spec.num_shards
    me = jax.lax.axis_index(ax)
    # ledger bill: id all_gather plus the [nshard*B, D] sum-scatter
    # (trace-time record only — tpu-lint TPU001)
    register_collective(
        "emb_lookup", ax,
        nshard * ids.shape[0] * 4
        + nshard * ids.shape[0] * table.shape[-1]
        * table.dtype.itemsize)
    # every shard sees every slot's request list: [nshard * B]
    all_ids = jax.lax.all_gather(ids, ax, tiled=True)
    owner, local = _owner_and_local(jnp.maximum(all_ids, 0), spec)
    mine = (owner == me) & (all_ids >= 0)
    rows = jnp.take(table, jnp.where(mine, local, 0), axis=0)
    # dtype-explicit zero: gathered rows keep the TABLE dtype (bf16/
    # fp16 tables pull narrow bytes over ICI); callers pick the
    # compute dtype — a weak-typed literal here would leave that to
    # promotion rules that have shifted across jax versions
    rows = jnp.where(mine[:, None], rows, jnp.zeros((), table.dtype))
    # each requested row has exactly one owner -> sum-scatter returns
    # each slot its own [B, D] block
    return jax.lax.psum_scatter(rows, ax, scatter_dimension=0, tiled=True)


def sharded_push_adagrad(table, state, ids, grads, spec: ShardedTableSpec,
                         lr: float, eps: float = 1e-10,
                         reduce_axis: Optional[str] = None
                         ) -> Tuple[jax.Array, jax.Array]:
    """Collective push with owner-side row-sparse Adagrad.

    Semantics parity with the reference's server-side update
    (kvserver.py:41-57): ``state[row] += mean(grad^2, -1)`` then
    ``row -= lr * grad / sqrt(state + eps)``; duplicate ids in a batch
    accumulate first (additive PUSH, dis_kvstore.py:503-520).

    table/state: [rows_per_shard, D] / [rows_per_shard] local shards.
    ids, grads : [B] global ids, [B, D] gradients from this slot.

    ``reduce_axis``: on a dp x mp mesh where the table is sharded over
    ``spec.axis`` (mp) but REPLICATED over ``reduce_axis`` (dp), the
    accumulated gradients are psum'd over the replica axis before the
    Adagrad update so every dp row's table copy stays identical — the
    role of the KVStore receiving pushes from every machine's trainer
    group (dis_kvstore.py:757-815).
    Returns updated (table, state).
    """
    from dgl_operator_tpu.obs.comm import register_collective

    ax = spec.axis
    me = jax.lax.axis_index(ax)
    register_collective(
        "emb_push", ax,
        spec.num_shards * (ids.shape[0] * 4
                           + grads.shape[0] * grads.shape[-1]
                           * grads.dtype.itemsize))
    all_ids = jax.lax.all_gather(ids, ax, tiled=True)
    all_g = jax.lax.all_gather(grads, ax, tiled=True)
    owner, local = _owner_and_local(jnp.maximum(all_ids, 0), spec)
    mine = (owner == me) & (all_ids >= 0)
    # accumulate duplicate rows into the local shard image
    local_idx = jnp.where(mine, local, spec.rows_per_shard)  # spare row
    acc = jax.ops.segment_sum(
        jnp.where(mine[:, None], all_g, 0.0), local_idx,
        num_segments=spec.rows_per_shard + 1)[:-1]
    cnt = jax.ops.segment_sum(
        mine.astype(jnp.float32), local_idx,
        num_segments=spec.rows_per_shard + 1)[:-1]
    if reduce_axis is not None:
        acc = jax.lax.psum(acc, reduce_axis)
        cnt = jax.lax.psum(cnt, reduce_axis)
    touched = cnt > 0
    gsum = jnp.mean(acc * acc, axis=-1)
    new_state = state + jnp.where(touched, gsum, 0.0)
    step = acc * (lr / jnp.sqrt(new_state + eps))[:, None]
    new_table = table - jnp.where(touched[:, None], step, 0.0)
    return new_table, new_state


def bind_embedding_ops(mesh: Mesh, spec: ShardedTableSpec,
                       lookup_fn, push_fn):
    """Bind per-shard (lookup, push) bodies as jitted shard_map
    programs over ``mesh``. Single owner of the sharding contract —
    used by both the dense collectives here and the ring collectives
    in ``parallel.ring``.

    Returned callables take/return *global-view* arrays:
      lookup(table, ids)                  ids [nshard*B]  -> [nshard*B, D]
      push(table, state, ids, grads, lr)  -> (table, state)
    with table/state sharded over rows and ids/grads sharded over batch.
    """
    ax = spec.axis
    shard_rows = NamedSharding(mesh, P(ax))
    shard_batch = NamedSharding(mesh, P(ax))

    lookup = jax.jit(shard_map(
        partial(lookup_fn, spec=spec),
        mesh=mesh, in_specs=(P(ax), P(ax)), out_specs=P(ax)))

    def _push(table, state, ids, grads, lr):
        return push_fn(table, state, ids, grads, spec, lr)

    push = jax.jit(shard_map(
        _push, mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax), P(ax), P()),
        out_specs=(P(ax), P(ax))))
    return lookup, push, shard_rows, shard_batch


def make_embedding_ops(mesh: Mesh, spec: ShardedTableSpec):
    """Dense-collective bindings (all_gather + psum_scatter bodies)."""
    return bind_embedding_ops(mesh, spec, sharded_lookup,
                              sharded_push_adagrad)


# ----------------------------------------------------------------------
# Host-side reference semantics (used by tests and the single-device path)
def dense_lookup(table, ids):
    return jnp.take(table, jnp.maximum(ids, 0), axis=0) * (ids >= 0)[:, None]


def dense_push_adagrad(table, state, ids, grads, lr, eps=1e-10):
    """Unsharded reference of the same update, for parity checks."""
    table = np.array(table, dtype=np.float64)
    state = np.array(state, dtype=np.float64)
    grads = np.asarray(grads, dtype=np.float64)
    acc = {}
    for i, g in zip(np.asarray(ids), grads):
        if i < 0:
            continue
        acc[int(i)] = acc.get(int(i), 0.0) + g
    for i, g in acc.items():
        state[i] += float(np.mean(g * g))
        table[i] -= lr * g / np.sqrt(state[i] + eps)
    return table, state
