"""Ring attention over a mesh-sharded neighbor/sequence axis.

Long-context support, graph-shaped. The reference's "long input" axis is
graph size (SURVEY.md §5): a hub node's full in-neighborhood at
inference time can exceed one device's memory the same way a long
sequence does in attention models. This module computes exact softmax
attention over an axis that is **sharded across the device mesh**,
blockwise, with the flash-attention streaming recurrence (running max /
denominator / numerator in log-sum-exp form) and one ``ppermute`` ring
rotation per hop — the canonical ICI pattern (pallas_guide "Ring
Collectives"; same recurrence as blockwise ring attention for
sequences). No shard ever materializes the full ``[N, S]`` score
matrix: peak live memory per shard is ``O(N * S/nshard)``.

Two scorers share the streaming core:

- :func:`ring_dot_attention` — scaled dot-product, the transformer /
  sequence-parallel form (queries stay put, key/value blocks ride the
  ring).
- :func:`ring_gat_attention` — GAT's additive scorer
  ``leaky_relu(el[u] + er[v])`` (nn/conv.py GATConv semantics; reference
  edge-softmax role), with the neighbor-side terms sharded. This is
  full-neighborhood GAT aggregation for nodes whose degree exceeds a
  single shard.

Numerics: masked slots score ``-1e30`` (finite, so the max/correction
algebra never sees inf-inf NaNs) and probabilities are additionally
multiplied by the mask; rows with zero valid slots yield 0 — the same
zero-in-degree convention as ``ops.fanout`` / ``ops.segment``.

Parity contract (tests/test_ring_attention.py): each ring form equals
its dense single-device reference to float tolerance on the 8-device
CPU mesh, sharded via shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from dgl_operator_tpu.parallel.ring import _ring_perm

_NEG = -1e30


def _stream_block(carry, logits, mask, v):
    """One blockwise update of the streaming-softmax state.

    carry = (m [N,H] running max, d [N,H] denominator,
             o [N,H,D] numerator); logits [N,S,H]; mask [N,S];
    v [N,S,H,D].
    """
    m, d, o = carry
    logits = jnp.where(mask[:, :, None] > 0, logits, _NEG)
    m_new = jnp.maximum(m, logits.max(axis=1))
    corr = jnp.exp(m - m_new)                      # [N,H]
    p = jnp.exp(logits - m_new[:, None, :])
    p = p * mask[:, :, None].astype(p.dtype)       # [N,S,H]
    d = d * corr + p.sum(axis=1)
    o = o * corr[..., None] + jnp.einsum("nsh,nshd->nhd", p, v)
    return m_new, d, o


def _ring_stream(score: Callable, fixed, blk, mask, v, axis: str):
    """Run the streaming recurrence over every shard's block, rotating
    (blk, mask, v) one hop per step. Runs inside shard_map over
    ``axis``; returns [N, H, D] (identical on every shard)."""
    n = jax.lax.axis_size(axis)
    N, _, H = score(fixed, blk).shape
    D = v.shape[-1]
    m0 = jnp.full((N, H), _NEG, jnp.float32)
    d0 = jnp.zeros((N, H), jnp.float32)
    o0 = jnp.zeros((N, H, D), jnp.float32)
    carry = _stream_block((m0, d0, o0), score(fixed, blk), mask, v)

    def hop(c, _):
        carry, blk, mask, v = c
        perm = _ring_perm(n)
        blk = jax.lax.ppermute(blk, axis, perm)
        mask = jax.lax.ppermute(mask, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        carry = _stream_block(carry, score(fixed, blk), mask, v)
        return (carry, blk, mask, v), ()

    (carry, _, _, _), _ = jax.lax.scan(
        hop, (carry, blk, mask, v), jnp.arange(1, n))
    _, d, o = carry
    return o / jnp.maximum(d, 1e-20)[..., None]


def _dot_score(q, k):
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    return jnp.einsum("nhd,nshd->nsh", q, k) * scale


def ring_dot_attention(q, k, v, mask, axis: str):
    """Exact softmax attention with the key axis sharded over ``axis``.

    Shapes (per shard, inside shard_map): q [N,H,Dk] replicated;
    k [N,S/n,H,Dk], v [N,S/n,H,Dv], mask [N,S/n] sharded. Returns
    [N,H,Dv] replicated.
    """
    return _ring_stream(_dot_score, q, k, mask, v, axis)


def ring_gat_attention(el, er, v, mask, axis: str,
                       negative_slope: float = 0.2):
    """GAT additive-attention aggregation with the neighbor axis
    sharded over ``axis``.

    Shapes (per shard): er [N,H] replicated (dst term); el [N,S/n,H],
    v [N,S/n,H,D], mask [N,S/n] sharded (neighbor terms). Scoring
    matches nn.conv.FanoutGATConv: ``leaky_relu(el + er)`` then
    masked softmax over the full sharded neighbor axis.
    """
    def score(er_, el_):
        return jax.nn.leaky_relu(el_ + er_[:, None, :],
                                 negative_slope=negative_slope)

    return _ring_stream(score, er, el, mask, v, axis)


# ---------------------------------------------------------------------
# dense single-device references (parity targets + small-input path)

def dense_dot_attention(q, k, v, mask):
    logits = jnp.where(mask[:, :, None] > 0, _dot_score(q, k), _NEG)
    p = jax.nn.softmax(logits, axis=1) * mask[:, :, None]
    d = jnp.maximum(p.sum(axis=1), 1e-20)
    return jnp.einsum("nsh,nshd->nhd", p, v) / d[..., None]


def dense_gat_attention(el, er, v, mask, negative_slope: float = 0.2):
    logits = jax.nn.leaky_relu(el + er[:, None, :], negative_slope)
    logits = jnp.where(mask[:, :, None] > 0, logits, _NEG)
    p = jax.nn.softmax(logits, axis=1) * mask[:, :, None]
    d = jnp.maximum(p.sum(axis=1), 1e-20)
    return jnp.einsum("nsh,nshd->nhd", p, v) / d[..., None]


def gathered_gat_attention(el_full, er_dst, feat, nbr, mask, axis: str,
                           negative_slope: float = 0.2):
    """GAT attention over full neighbor lists whose INDEX arrays are
    sharded, with the node table replicated — the hub-node inference
    layout (models/gat.py ``gat_hub_attention``).

    Runs inside shard_map: ``nbr``/``mask`` [B, S/n] sharded over
    ``axis``; ``el_full`` [N, H], ``feat`` [N, H, D], ``er_dst``
    [B, H] replicated. Each shard gathers ONLY its slice (the
    [B, S/n, H, D] gathered tensor never exists globally), computes
    partial streaming-softmax stats, and the shards combine with one
    ``pmax`` + two ``psum``s in log-sum-exp form — cheaper than a ring
    when the table is replicated (no [.., S/n, ..] block ever moves;
    only the [B, H(,D)] stats cross ICI)."""
    el_loc = el_full[nbr]                       # [B, S/n, H]
    v_loc = feat[nbr]                           # [B, S/n, H, D]
    logits = jax.nn.leaky_relu(el_loc + er_dst[:, None, :],
                               negative_slope=negative_slope)
    m_l, d_l, o_l = _stream_block(
        (jnp.full(er_dst.shape, _NEG, jnp.float32),
         jnp.zeros(er_dst.shape, jnp.float32),
         jnp.zeros(er_dst.shape + (feat.shape[-1],), jnp.float32)),
        logits, mask, v_loc)
    m_g = jax.lax.pmax(m_l, axis)
    corr = jnp.exp(m_l - m_g)
    d = jax.lax.psum(d_l * corr, axis)
    o = jax.lax.psum(o_l * corr[..., None], axis)
    return o / jnp.maximum(d, 1e-20)[..., None]


# ---------------------------------------------------------------------

_BIND_CACHE: dict = {}


def make_ring_attention(mesh, axis: str = "mp", mode: str = "dot",
                        **kw):
    """Jitted shard_map binding: global arrays with the S axis sharded
    over ``axis``, output replicated. ``mode``:

    - "dot": ``(q, k, v, mask)`` — ring over sharded K/V blocks.
    - "gat": ``(el, er, v, mask)`` — ring over sharded neighbor terms.
    - "gat-gathered": ``(el_full, er_dst, feat, nbr, mask)`` — sharded
      index lists into a replicated table, log-sum-exp psum combine.

    Bindings are cached per (mesh, axis, mode, kwargs) so repeated
    calls reuse one jitted callable (jit's cache is keyed on function
    identity); the cache is bounded (FIFO, 8 entries) so long-lived
    processes that churn meshes don't pin compiled executables
    forever."""
    key = (mesh, axis, mode, tuple(sorted(kw.items())))
    hit = _BIND_CACHE.pop(key, None)
    if hit is not None:
        _BIND_CACHE[key] = hit      # LRU refresh, not FIFO
        return hit
    from jax.sharding import PartitionSpec as P
    shard_map = jax.shard_map

    if mode == "dot":
        if kw:
            raise TypeError(f"mode='dot' takes no extra kwargs: {kw}")
        fn = partial(ring_dot_attention, axis=axis)
        in_specs = (P(), P(None, axis), P(None, axis), P(None, axis))
    elif mode == "gat":
        fn = (lambda el, er, v, mask:
              ring_gat_attention(el, er, v, mask, axis=axis, **kw))
        in_specs = (P(None, axis), P(), P(None, axis), P(None, axis))
    elif mode == "gat-gathered":
        fn = (lambda el_full, er_dst, feat, nbr, mask:
              gathered_gat_attention(el_full, er_dst, feat, nbr, mask,
                                     axis=axis, **kw))
        in_specs = (P(), P(), P(), P(None, axis), P(None, axis))
    else:
        raise ValueError(f"unknown mode {mode!r}")
    bound = jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=P(), check_vma=False))
    while len(_BIND_CACHE) >= 8:
        _BIND_CACHE.pop(next(iter(_BIND_CACHE)))
    _BIND_CACHE[key] = bound
    return bound
