"""Ring attention over a mesh-sharded neighbor/sequence axis.

Long-context support, graph-shaped. The reference's "long input" axis is
graph size (SURVEY.md §5): a hub node's full in-neighborhood at
inference time can exceed one device's memory the same way a long
sequence does in attention models. This module computes exact softmax
attention over an axis that is **sharded across the device mesh**,
blockwise, with the flash-attention streaming recurrence (running max /
denominator / numerator in log-sum-exp form) and one ``ppermute`` ring
rotation per hop — the canonical ICI pattern (pallas_guide "Ring
Collectives"; same recurrence as blockwise ring attention for
sequences). No shard ever materializes the full ``[N, S]`` score
matrix: peak live memory per shard is ``O(N * S/nshard)``.

Two scorers share the streaming core:

- :func:`ring_dot_attention` — scaled dot-product, the transformer /
  sequence-parallel form (queries stay put, key/value blocks ride the
  ring).
- :func:`ring_gat_attention` — GAT's additive scorer
  ``leaky_relu(el[u] + er[v])`` (nn/conv.py GATConv semantics; reference
  edge-softmax role), with the neighbor-side terms sharded. This is
  full-neighborhood GAT aggregation for nodes whose degree exceeds a
  single shard.

Numerics: masked slots score ``-1e30`` (finite, so the max/correction
algebra never sees inf-inf NaNs) and probabilities are additionally
multiplied by the mask; rows with zero valid slots yield 0 — the same
zero-in-degree convention as ``ops.fanout`` / ``ops.segment``.

Parity contract (tests/test_ring_attention.py): each ring form equals
its dense single-device reference to float tolerance on the 8-device
CPU mesh, sharded via shard_map.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from dgl_operator_tpu.parallel.ring import _ring_perm

_NEG = -1e30

# measured ring-vs-dense scaling artifact (benchmarks/bench_scaling.py
# writes it; bench.py's scaling child refreshes it every round) — the
# data behind mode="auto"'s perf rule, like KERNELS_TPU.json for
# use_pallas()
_RING_RECORD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "RING_SCALING.json")
_ring_record_cache: dict = {}
_ring_stat_cache: list = []     # [(monotonic expiry, mtime_ns|None)]
_budget_cache: list = []        # [(monotonic expiry, bytes)]


def dense_attention_bytes(N: int, S: int, H: int, Dk: int, Dv: int,
                          itemsize: int = 4) -> int:
    """Single-device live footprint of the dense form: K and V resident
    plus the [N,S,H] logits and probabilities the softmax materializes.
    (The ring form's per-shard version of the same is 1/nshard of
    this — its whole point.)"""
    return N * S * H * (Dk + Dv + 2) * itemsize


def recorded_crossover(platform: Optional[str] = None
                       ) -> "Optional[dict]":
    """Measured ring/dense latency crossover from the scaling artifact
    (``{"crossover_s": S, "shape": {N, H, ...}}``), or None when no
    measurement for this platform exists (the memory rule still
    applies). The artifact keys records per platform (the CPU scaling
    child and a TPU bench run each write their own entry, neither
    clobbers the other), and the cache is keyed on the file's mtime so
    a refresh lands without a process restart."""
    # the stat lives on the mode="auto" hot path (one call per
    # attention step): a short TTL bounds syscall traffic while a
    # refreshed artifact still lands within ~2 s, no restart needed
    now = time.monotonic()
    if _ring_stat_cache and _ring_stat_cache[0][0] > now:
        mtime = _ring_stat_cache[0][1]
    else:
        try:
            mtime = os.stat(_RING_RECORD).st_mtime_ns
        except OSError:
            mtime = None
        _ring_stat_cache[:] = [(now + 2.0, mtime)]
    key = (platform or "any", mtime)
    if key in _ring_record_cache:
        return _ring_record_cache[key]
    result = None
    if mtime is not None:
        try:
            with open(_RING_RECORD) as f:
                rec = json.load(f).get("platforms", {})
            entry = rec.get(platform) if platform else None
            if entry and entry.get("crossover_s") is not None:
                result = {"crossover_s": entry["crossover_s"],
                          "shape": entry.get("shape", {})}
        except Exception:  # noqa: BLE001 — unreadable record = no rule
            result = None
    _ring_record_cache.clear()      # one live generation at a time
    _ring_record_cache[key] = result
    return result


def _device_budget_bytes() -> int:
    """Per-device memory budget the dense form may spend on attention.
    Override with DGL_TPU_ATTN_BUDGET_BYTES; else half the device's
    free memory when the backend reports it (TPU does), else a 4 GiB
    default (CPU hosts)."""
    env = os.environ.get("DGL_TPU_ATTN_BUDGET_BYTES")
    if env:
        return int(env)
    # memory_stats is a runtime round-trip and this sits on the
    # mode="auto" hot path — TTL-cache it; the env override above
    # stays per-call (tests and operators flip it live)
    now = time.monotonic()
    if _budget_cache and _budget_cache[0][0] > now:
        return _budget_cache[0][1]
    try:
        stats = jax.devices()[0].memory_stats()
        free = stats["bytes_limit"] - stats["bytes_in_use"]
        val = max(free // 2, 1)
    except Exception:  # noqa: BLE001 — backend without memory_stats
        val = 4 << 30
    _budget_cache[:] = [(now + 5.0, val)]
    return val


def use_ring(N: int, S: int, H: int, Dk: int, Dv: int,
             itemsize: int = 4,
             budget_bytes: Optional[int] = None,
             crossover: Optional[dict] = None,
             nshard: Optional[int] = None) -> bool:
    """mode="auto" dispatch rule (the use_pallas() analogue): ring when

    - the MEASURED latency crossover says ring is faster at this much
      work (scaling artifact, perf rule) — compared on total score
      elements ``N*S*H``, not bare S, so a crossover measured at N=64
      doesn't misfire ring for a tiny-N call whose hop overhead would
      dominate; or
    - the dense form's single-device footprint exceeds the memory
      budget (capability rule: dense would OOM; ring's per-shard
      footprint is 1/nshard and streams the rest over the ring).

    Small inputs stay dense — the r3 lesson: at [64, 1024, 4, 32] the
    ring's hop overhead lost to dense by 9x; ring must earn its place
    by measured work, not be the default.
    """
    if crossover is None:
        crossover = recorded_crossover(jax.default_backend())
    if crossover and crossover.get("crossover_s") is not None:
        shp = crossover.get("shape", {})
        # the perf rule only transfers between equal mesh widths: ring
        # cost scales with hop count and per-hop block size, so a
        # crossover measured on an 8-way mesh says nothing about a
        # 2-way one — mismatched shard counts fall through to the
        # memory rule (still "measured, not default")
        rec_shards = shp.get("shards")
        if (nshard is None or rec_shards is None
                or rec_shards == nshard):
            work_at_crossover = (shp.get("N", 1)
                                 * crossover["crossover_s"]
                                 * shp.get("H", 1))
            if N * S * H >= work_at_crossover:
                return True
    if budget_bytes is None:
        budget_bytes = _device_budget_bytes()
    return dense_attention_bytes(N, S, H, Dk, Dv, itemsize) > budget_bytes


def _stream_block(carry, logits, mask, v):
    """One blockwise update of the streaming-softmax state.

    carry = (m [N,H] running max, d [N,H] denominator,
             o [N,H,D] numerator); logits [N,S,H]; mask [N,S];
    v [N,S,H,D].
    """
    m, d, o = carry
    logits = jnp.where(mask[:, :, None] > 0, logits, _NEG)
    m_new = jnp.maximum(m, logits.max(axis=1))
    corr = jnp.exp(m - m_new)                      # [N,H]
    p = jnp.exp(logits - m_new[:, None, :])
    p = p * mask[:, :, None].astype(p.dtype)       # [N,S,H]
    d = d * corr + p.sum(axis=1)
    o = o * corr[..., None] + jnp.einsum("nsh,nshd->nhd", p, v)
    return m_new, d, o


def _ring_stream(score: Callable, fixed, blk, mask, v, axis: str):
    """Run the streaming recurrence over every shard's block, rotating
    (blk, mask, v) one hop per step. Runs inside shard_map over
    ``axis``; returns [N, H, D] (identical on every shard)."""
    from dgl_operator_tpu.parallel.mesh import body_axis_size
    n = body_axis_size(axis)
    N, _, H = score(fixed, blk).shape
    D = v.shape[-1]
    m0 = jnp.full((N, H), _NEG, jnp.float32)
    d0 = jnp.zeros((N, H), jnp.float32)
    o0 = jnp.zeros((N, H, D), jnp.float32)
    carry = _stream_block((m0, d0, o0), score(fixed, blk), mask, v)

    def hop(c, _):
        carry, blk, mask, v = c
        perm = _ring_perm(n)
        blk = jax.lax.ppermute(blk, axis, perm)
        mask = jax.lax.ppermute(mask, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        carry = _stream_block(carry, score(fixed, blk), mask, v)
        return (carry, blk, mask, v), ()

    (carry, _, _, _), _ = jax.lax.scan(
        hop, (carry, blk, mask, v), jnp.arange(1, n))
    _, d, o = carry
    return o / jnp.maximum(d, 1e-20)[..., None]


def _dot_score(q, k):
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    return jnp.einsum("nhd,nshd->nsh", q, k) * scale


def ring_dot_attention(q, k, v, mask, axis: str):
    """Exact softmax attention with the key axis sharded over ``axis``.

    Shapes (per shard, inside shard_map): q [N,H,Dk] replicated;
    k [N,S/n,H,Dk], v [N,S/n,H,Dv], mask [N,S/n] sharded. Returns
    [N,H,Dv] replicated.
    """
    return _ring_stream(_dot_score, q, k, mask, v, axis)


def ring_gat_attention(el, er, v, mask, axis: str,
                       negative_slope: float = 0.2):
    """GAT additive-attention aggregation with the neighbor axis
    sharded over ``axis``.

    Shapes (per shard): er [N,H] replicated (dst term); el [N,S/n,H],
    v [N,S/n,H,D], mask [N,S/n] sharded (neighbor terms). Scoring
    matches nn.conv.FanoutGATConv: ``leaky_relu(el + er)`` then
    masked softmax over the full sharded neighbor axis.
    """
    def score(er_, el_):
        return jax.nn.leaky_relu(el_ + er_[:, None, :],
                                 negative_slope=negative_slope)

    return _ring_stream(score, er, el, mask, v, axis)


# ---------------------------------------------------------------------
# dense single-device references (parity targets + small-input path)

def dense_dot_attention(q, k, v, mask):
    logits = jnp.where(mask[:, :, None] > 0, _dot_score(q, k), _NEG)
    p = jax.nn.softmax(logits, axis=1) * mask[:, :, None]
    d = jnp.maximum(p.sum(axis=1), 1e-20)
    return jnp.einsum("nsh,nshd->nhd", p, v) / d[..., None]


def dense_gat_attention(el, er, v, mask, negative_slope: float = 0.2):
    logits = jax.nn.leaky_relu(el + er[:, None, :], negative_slope)
    logits = jnp.where(mask[:, :, None] > 0, logits, _NEG)
    p = jax.nn.softmax(logits, axis=1) * mask[:, :, None]
    d = jnp.maximum(p.sum(axis=1), 1e-20)
    return jnp.einsum("nsh,nshd->nhd", p, v) / d[..., None]


def gathered_gat_attention(el_full, er_dst, feat, nbr, mask, axis: str,
                           negative_slope: float = 0.2):
    """GAT attention over full neighbor lists whose INDEX arrays are
    sharded, with the node table replicated — the hub-node inference
    layout (models/gat.py ``gat_hub_attention``).

    Runs inside shard_map: ``nbr``/``mask`` [B, S/n] sharded over
    ``axis``; ``el_full`` [N, H], ``feat`` [N, H, D], ``er_dst``
    [B, H] replicated. Each shard gathers ONLY its slice (the
    [B, S/n, H, D] gathered tensor never exists globally), computes
    partial streaming-softmax stats, and the shards combine with one
    ``pmax`` + two ``psum``s in log-sum-exp form — cheaper than a ring
    when the table is replicated (no [.., S/n, ..] block ever moves;
    only the [B, H(,D)] stats cross ICI)."""
    el_loc = el_full[nbr]                       # [B, S/n, H]
    v_loc = feat[nbr]                           # [B, S/n, H, D]
    logits = jax.nn.leaky_relu(el_loc + er_dst[:, None, :],
                               negative_slope=negative_slope)
    m_l, d_l, o_l = _stream_block(
        (jnp.full(er_dst.shape, _NEG, jnp.float32),
         jnp.zeros(er_dst.shape, jnp.float32),
         jnp.zeros(er_dst.shape + (feat.shape[-1],), jnp.float32)),
        logits, mask, v_loc)
    m_g = jax.lax.pmax(m_l, axis)
    corr = jnp.exp(m_l - m_g)
    d = jax.lax.psum(d_l * corr, axis)
    o = jax.lax.psum(o_l * corr[..., None], axis)
    return o / jnp.maximum(d, 1e-20)[..., None]


# ---------------------------------------------------------------------

_BIND_CACHE: dict = {}


def _cache_put(key, fn):
    """Bounded (LRU, 8 entries) insert shared by every binding path."""
    while len(_BIND_CACHE) >= 8:
        _BIND_CACHE.pop(next(iter(_BIND_CACHE)))
    _BIND_CACHE[key] = fn
    return fn


def make_ring_attention(mesh, axis: str = "mp", mode: str = "dot",
                        **kw):
    """Jitted shard_map binding: global arrays with the S axis sharded
    over ``axis``, output replicated. ``mode``:

    - "dot": ``(q, k, v, mask)`` — ring over sharded K/V blocks.
    - "gat": ``(el, er, v, mask)`` — ring over sharded neighbor terms.
    - "gat-gathered": ``(el_full, er_dst, feat, nbr, mask)`` — sharded
      index lists into a replicated table, log-sum-exp psum combine.
    - "auto" / "auto-gat": per-call dispatch between the dense
      single-device form and the ring, by :func:`use_ring` (measured
      latency crossover when the scaling artifact has one, else the
      dense-footprint-vs-memory-budget rule). Dense parity is exact:
      both forms share the same scorer and masking algebra.

    Bindings are cached per (mesh, axis, mode, kwargs) so repeated
    calls reuse one jitted callable (jit's cache is keyed on function
    identity); the cache is bounded (LRU, 8 entries) so long-lived
    processes that churn meshes don't pin compiled executables
    forever."""
    key = (mesh, axis, mode, tuple(sorted(kw.items())))
    hit = _BIND_CACHE.pop(key, None)
    if hit is not None:
        _BIND_CACHE[key] = hit      # LRU refresh, not FIFO
        return hit
    from jax.sharding import PartitionSpec as P
    from dgl_operator_tpu.parallel.mesh import shard_map

    if mode in ("auto", "auto-gat"):
        gat = mode == "auto-gat"
        ring = make_ring_attention(mesh, axis,
                                   "gat" if gat else "dot", **kw)
        dense = jax.jit(partial(dense_gat_attention, **kw) if gat
                        else dense_dot_attention)

        nshard = int(mesh.shape[axis])

        def auto(a, b, v, mask):
            # a=q [N,H,Dk] / b=k for dot; a=el [N,S,H] / b=er for gat
            N, S = mask.shape
            H, Dv = v.shape[-2], v.shape[-1]
            Dk = a.shape[-1] if not gat else 1
            if use_ring(N, S, H, Dk, Dv,
                        itemsize=jnp.asarray(v).dtype.itemsize,
                        nshard=nshard):
                return ring(a, b, v, mask)
            return dense(a, b, v, mask)

        return _cache_put(key, auto)

    if mode == "dot":
        if kw:
            raise TypeError(f"mode='dot' takes no extra kwargs: {kw}")
        fn = partial(ring_dot_attention, axis=axis)
        in_specs = (P(), P(None, axis), P(None, axis), P(None, axis))
    elif mode == "gat":
        fn = (lambda el, er, v, mask:
              ring_gat_attention(el, er, v, mask, axis=axis, **kw))
        in_specs = (P(None, axis), P(), P(None, axis), P(None, axis))
    elif mode == "gat-gathered":
        fn = (lambda el_full, er_dst, feat, nbr, mask:
              gathered_gat_attention(el_full, er_dst, feat, nbr, mask,
                                     axis=axis, **kw))
        in_specs = (P(), P(), P(), P(None, axis), P(None, axis))
    else:
        raise ValueError(f"unknown mode {mode!r}")
    bound = jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=P(), check_vma=False))
    return _cache_put(key, bound)
