"""Rule-driven parameter / optimizer-state sharding.

The reference's KVStore exists because entity/relation tables and their
optimizer moments do NOT fit one worker (PAPER.md §KVStore,
dis_kvstore.py / kvserver.py's sparse-Adagrad server). The TPU-native
generalization is declarative: a list of ``(regex, PartitionSpec)``
rules maps every parameter's tree path to a placement over the
(dp, mp) mesh — the ``match_partition_rules`` idiom (SNIPPETS.md [2])
— and the optimizer state inherits each parameter's placement
automatically, so Adam/Adagrad moments land sharded 1/N exactly where
their parameter does (arXiv:2004.13336, ZeRO-style weight-update
sharding; PAPERS.md).

Contract:

- rules are ``(pattern, spec)`` pairs, first match wins
  (``re.search`` over the '/'-joined tree path);
- scalar leaves (ndim 0 or size 1 — Adam's step count) are ALWAYS
  replicated, before any rule is consulted;
- a non-scalar leaf no rule matches is a loud ``ValueError`` naming
  the path — silent replication is how a billion-row table quietly
  stops fitting;
- optimizer-state placement is derived, never written by hand: a
  moment leaf inherits the spec of the parameter whose path is the
  longest suffix of its own (optax wraps the params tree in its state
  namedtuples, so ``.../mu/layer0/kernel`` inherits ``layer0/kernel``),
  scalars stay replicated, and anything else defaults to replicated.

``spec`` in a rule may be a ``PartitionSpec``, ``None`` (replicated),
an axis name string, or a tuple of axis names — ``to_pspec`` owns the
coercion so config files can carry plain strings.
"""

from __future__ import annotations

import difflib
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def to_pspec(spec) -> P:
    """Coerce a rule's target into a ``PartitionSpec``: ``P`` objects
    pass through, ``None`` -> replicated, a string names one mesh axis,
    a tuple/list names several (each entry an axis name or None)."""
    if isinstance(spec, P):
        return spec
    if spec is None:
        return P()
    if isinstance(spec, str):
        return P(spec)
    if isinstance(spec, (tuple, list)):
        return P(*spec)
    raise TypeError(f"cannot coerce {spec!r} to a PartitionSpec")


def _key_name(k) -> str:
    """One tree_flatten_with_path key entry -> its path segment."""
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)  # pragma: no cover - exotic pytree node


def tree_paths(tree, sep: str = "/") -> List[Tuple[str, Any]]:
    """Flatten ``tree`` into ``(path, leaf)`` pairs with '/'-joined
    string paths — the names the rules match against."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(sep.join(_key_name(k) for k in kp), leaf)
            for kp, leaf in flat]


def is_scalar_leaf(leaf) -> bool:
    """Replicate-always leaves: ndim 0 or a single element (Adam's
    count). Works on arrays and ShapeDtypeStructs alike."""
    shape = tuple(getattr(leaf, "shape", ()))
    return len(shape) == 0 or int(np.prod(shape, dtype=int)) == 1


def spec_axes(spec) -> Tuple[str, ...]:
    """All mesh axis names a spec shards over, flattened positionally
    (``P(None, ("dp", "mp"))`` -> ``("dp", "mp")``)."""
    out: List[str] = []
    for entry in to_pspec(spec):
        out.extend((entry,) if isinstance(entry, str) else (entry or ()))
    return tuple(out)


def match_partition_rules(rules: Sequence[Tuple[str, Any]], params,
                          sep: str = "/"):
    """Map ``rules`` (ordered ``(regex, spec)`` pairs, first match
    wins) over ``params``, returning a pytree of ``PartitionSpec`` with
    the same structure. Scalar leaves short-circuit to replicated, and
    so does any leaf whose matched spec carries MORE positional entries
    than the leaf has dims (a hidden-dim TP rule sweeping up a 0-d gain
    scalar or a 1-d bias must degrade to replicated, not blow up at
    placement). A non-scalar leaf no rule matches raises ``ValueError``
    naming its path and the three nearest rule patterns (add a
    catch-all ``(".*", None)`` rule for explicit replicate-the-rest)."""
    compiled = [(pat, re.compile(pat), to_pspec(spec))
                for pat, spec in rules]

    def spec_of(name: str, leaf):
        if is_scalar_leaf(leaf):
            return P()
        ndim = len(tuple(getattr(leaf, "shape", ())))
        for _, rx, ps in compiled:
            if rx.search(name) is not None:
                if len(tuple(ps)) > ndim:
                    return P()
                return ps
        near = difflib.get_close_matches(
            name, [pat for pat, _, _ in compiled], n=3, cutoff=0.0)
        hint = ("; nearest rule patterns: "
                + ", ".join(repr(p) for p in near)) if near else ""
        raise ValueError(
            f"no partition rule matches param {name!r} "
            "(rules are first-match-wins; add a catch-all "
            f"('.*', None) to replicate unmatched leaves{hint})")

    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = [spec_of(sep.join(_key_name(k) for k in kp), leaf)
              for kp, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


def opt_state_specs(opt_state, params, param_specs, sep: str = "/"):
    """Placement pytree for an optax state, derived from the params'
    placement: every moment leaf inherits the spec of the parameter
    whose path is the longest suffix of the leaf's own path (optax
    embeds the params tree inside its state namedtuples); leaves with
    no parameter ancestry (Adam's count, mu_dtype bookkeeping) stay
    replicated.

    Shapes are deliberately NOT compared: under weight-update sharding
    the moments live as flattened per-device shards whose shapes never
    match their parameter's (parallel/dp.py), but their tree paths
    still carry the parameter's path as a suffix. Ancestry wins over
    the scalar heuristic for the same reason: a small param's per-slot
    moment shard can degenerate to a single element (size <= dp width)
    and must STILL carry its param's sharded spec — classifying it as
    a scalar would mis-assemble the moment's global array from one
    device's shard (ISSUE 16).
    """
    by_path = {path: spec for (path, _), (_, spec) in
               zip(tree_paths(params, sep), tree_paths(param_specs, sep))}

    def inherit(path: str, leaf):
        best = None
        for ppath, spec in by_path.items():
            if path == ppath or path.endswith(sep + ppath):
                if best is None or len(ppath) > len(best[0]):
                    best = (ppath, spec)
        if best is not None:
            return best[1]
        return P()

    flat = jax.tree_util.tree_flatten_with_path(opt_state)
    leaves = [inherit(sep.join(_key_name(k) for k in kp), leaf)
              for kp, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


def place_by_specs(mesh: Mesh, tree, specs):
    """Place every leaf of ``tree`` on ``mesh`` under its spec.
    Multi-controller: each process passes the SAME host value (same
    seed / same checkpoint) and contributes its addressable shards —
    the ``place_host_array`` contract (parallel/embedding.py)."""
    from dgl_operator_tpu.parallel.embedding import place_host_array
    return jax.tree.map(
        lambda x, s: place_host_array(mesh, x, to_pspec(s)), tree, specs)


# ---------------------------------------------------------------------
# HBM accounting — the analytic model the scale bench, the trainers'
# gauges and tpu-doctor all read (single owner, so the numbers agree).
# ---------------------------------------------------------------------
def _leaf_bytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()))
    dt = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=int)) * dt.itemsize


def bytes_per_slot(tree, specs, axis_sizes: Dict[str, int]) -> int:
    """Per-mesh-slot persistent bytes of ``tree`` under ``specs``: each
    leaf's bytes divided by the product of the sizes of the mesh axes
    its spec shards over (ceil — padding rows bill the shard that
    carries them)."""
    total = 0
    for (_, leaf), (_, spec) in zip(tree_paths(tree), tree_paths(specs)):
        n = 1
        for entry in to_pspec(spec):
            for ax in ((entry,) if isinstance(entry, str) else
                       (entry or ())):
                n *= int(axis_sizes[ax])
        total += -(-_leaf_bytes(leaf) // n)
    return total


def replicated_bytes(tree) -> int:
    """Per-slot bytes with everything replicated — the baseline the
    savings ratio is quoted against."""
    return sum(_leaf_bytes(leaf) for _, leaf in tree_paths(tree))


def zero3_bytes_per_slot(params, num_parts: int) -> int:
    """Per-slot PERSISTENT param bytes under the ``zero_stage=3`` flat
    storage plan (parallel/dp.py): every leaf flattened, zero-padded
    to a multiple of the dp width and split, so each slot holds
    ceil(size/n) elements — the padding bills the shard that carries
    it. Leaves a TP rule routes to a dim plan bill through
    :func:`bytes_per_slot` with their emitted specs instead; this is
    the rules-free default every unmatched leaf falls back to, and
    the number ``params_mib_per_slot_zero3`` in the scale bench's
    ``hbm_budget`` block is quoted from (benchkeys.SCALE_FULL_KEYS)."""
    n = max(int(num_parts), 1)
    total = 0
    for _, leaf in tree_paths(params):
        size = int(np.prod(tuple(getattr(leaf, "shape", ())),
                           dtype=int))
        itemsize = np.dtype(getattr(leaf, "dtype",
                                    np.float32)).itemsize
        total += -(-size // n) * itemsize
    return total


def sharding_summary(params, opt_state, param_specs, opt_specs,
                     axis_sizes: Dict[str, int]) -> Dict[str, float]:
    """The state-sharding HBM block (MiB per slot, replicated vs
    sharded, plus the savings ratio) — emitted as gauges by the
    trainers, embedded in ``hbm_budget`` by the scale bench, rendered
    by ``tpu-doctor``. Keys are pinned by tests/test_shardrules.py."""
    p_rep = replicated_bytes(params)
    o_rep = replicated_bytes(opt_state)
    p_sh = bytes_per_slot(params, param_specs, axis_sizes)
    o_sh = bytes_per_slot(opt_state, opt_specs, axis_sizes)
    mib = 1.0 / 2**20
    return {
        "params_mib_per_slot_replicated": round(p_rep * mib, 3),
        "params_mib_per_slot_sharded": round(p_sh * mib, 3),
        "opt_state_mib_per_slot_replicated": round(o_rep * mib, 3),
        "opt_state_mib_per_slot_sharded": round(o_sh * mib, 3),
        "state_savings_ratio": round(
            (p_sh + o_sh) / max(p_rep + o_rep, 1), 4),
    }


def emit_state_gauges(summary: Dict[str, float], role: str) -> None:
    """Fold a :func:`sharding_summary` into the obs registry as the
    ``train_state_mib_per_slot{role,kind,mode}`` gauge family plus
    ``train_state_savings_ratio{role}`` — the metrics the tpu-doctor
    "state sharding" block reads back from the job's metrics.json."""
    from dgl_operator_tpu.obs import get_obs
    g = get_obs().metrics.gauge(
        "train_state_mib_per_slot",
        "per-slot params/optimizer-state MiB under the active sharding",
        labels=("role", "kind", "mode"))
    for kind in ("params", "opt_state"):
        for mode in ("replicated", "sharded"):
            g.set(summary[f"{kind}_mib_per_slot_{mode}"],
                  role=role, kind=kind, mode=mode)
    get_obs().metrics.gauge(
        "train_state_savings_ratio",
        "sharded/replicated per-slot state bytes (1.0 = no sharding)",
        labels=("role",)).set(summary["state_savings_ratio"], role=role)


# ---------------------------------------------------------------------
# padded <-> logical conversions — the storage form ZeRO-3 persists
# (parallel/dp.py) is padding-carrying; checkpoints and cross-mesh
# restores go through the logical form, so pad/unpad has ONE owner.
# ---------------------------------------------------------------------
def pad_flat(arr, n: int):
    """Host-side: flatten and zero-pad to a multiple of ``n`` elements
    (the flat ZeRO shard storage form; pad elements carry zero grads
    forever, so elementwise optimizers leave them at zero)."""
    flat = np.asarray(arr).reshape(-1)
    pad = (-flat.size) % n
    return np.pad(flat, (0, pad)) if pad else flat


def pad_dims(arr, mults: Sequence[int]):
    """Host-side: zero-pad each dim of ``arr`` up to a multiple of the
    matching entry in ``mults`` (1 = leave alone) — the dim-sharded TP
    storage form."""
    arr = np.asarray(arr)
    widths = [(0, (-d) % m) for d, m in zip(arr.shape, mults)]
    if any(w for _, w in widths):
        return np.pad(arr, widths)
    return arr


def unpad_leaf(arr, shape: Sequence[int]):
    """Recover the logical leaf from its padded storage form: identity
    when shapes already agree, a flat ``[:size].reshape`` for 1-d flat
    shard storage, a per-dim slice for dim-padded storage. Raises when
    ``arr`` cannot contain a ``shape``-shaped leaf."""
    arr = np.asarray(arr)
    shape = tuple(int(s) for s in shape)
    if arr.shape == shape:
        return arr
    size = int(np.prod(shape, dtype=int))
    if arr.ndim == 1 and arr.size >= size:
        return arr[:size].reshape(shape)
    if arr.ndim == len(shape) and all(
            a >= s for a, s in zip(arr.shape, shape)):
        return arr[tuple(slice(0, s) for s in shape)]
    raise ValueError(
        f"cannot unpad a {arr.shape} storage leaf to logical shape "
        f"{shape}")
