"""Ring-collective sharded-embedding access (memory-lean pull/push).

The default KVStore replacement (``parallel.embedding``) implements
pull/push with ``all_gather`` + ``psum_scatter``: simple, one fused XLA
collective, but every shard materializes the full ``[nshard*B, D]``
request image in HBM. For large batches, wide rows, or big meshes that
buffer dominates memory.

This module provides the same semantics as a **ring program** built on
``jax.lax.ppermute`` — the canonical ICI pattern (pallas_guide "Ring
Collectives"; reduce-scatter shape): each mesh slot's ``[B, D]``
accumulator travels the ring once, and every shard adds the rows it
owns as the accumulator passes through. Peak live buffer per shard is
``O(B·D)`` instead of ``O(nshard·B·D)``; total ICI bytes are identical
to the dense form ((nshard-1)·B·D — reduce-scatter is a ring
internally), and XLA overlaps each hop with the local take of the next
step (the ``lax.scan`` body has hop t+1's compute independent of hop
t's receive).

Semantics parity: `ring_lookup` == `embedding.sharded_lookup`,
`ring_push_adagrad` == `embedding.sharded_push_adagrad` (the KVStore
PUSH/PULL + server-side sparse-Adagrad contract,
dis_kvstore.py:757-902, kvserver.py:41-57) — asserted against each
other in tests on the 8-device CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from dgl_operator_tpu.parallel.embedding import (ShardedTableSpec,
                                                 _owner_and_local)


def _ring_perm(nshard: int):
    return [(s, (s + 1) % nshard) for s in range(nshard)]


def ring_lookup(table, ids, spec: ShardedTableSpec):
    """Collective pull over a ring. Runs inside shard_map over
    ``spec.axis``; same contract as ``sharded_lookup``.

    At hop t, shard m holds the partially-filled answer for slot
    ``s = (m - 1 - t) mod n`` and adds its own rows for that slot's
    request list; after n-1 hops the accumulator lands on its owner.
    Request id lists are all-gathered once (ids are ~D× smaller than
    rows); only the [B, D] accumulator rides the ring.
    """
    from dgl_operator_tpu.obs.comm import register_collective

    ax = spec.axis
    n = spec.num_shards
    me = jax.lax.axis_index(ax)
    # ledger bill: the id all_gather plus n-1 ring hops of the [B, D]
    # accumulator (trace-time record only — tpu-lint TPU001)
    register_collective(
        "ring_lookup", ax,
        n * ids.shape[0] * 4
        + (n - 1) * ids.shape[0] * table.shape[-1]
        * table.dtype.itemsize)
    all_ids = jax.lax.all_gather(ids, ax)          # [n, B] (cheap)

    def contribution(slot):
        req = all_ids[slot]
        owner, local = _owner_and_local(jnp.maximum(req, 0), spec)
        mine = (owner == me) & (req >= 0)
        rows = jnp.take(table, jnp.where(mine, local, 0), axis=0)
        # table-dtype zero — same narrow-table contract as
        # embedding.sharded_lookup
        return jnp.where(mine[:, None], rows, jnp.zeros((), table.dtype))

    acc = contribution((me - 1) % n)

    def hop(acc, t):
        acc = jax.lax.ppermute(acc, ax, _ring_perm(n))
        acc = acc + contribution((me - 1 - t) % n)
        return acc, ()

    acc, _ = jax.lax.scan(hop, acc, jnp.arange(1, n))
    return acc


def ring_push_adagrad(table, state, ids, grads, spec: ShardedTableSpec,
                      lr: float, eps: float = 1e-10
                      ) -> Tuple[jax.Array, jax.Array]:
    """Collective push over a ring with owner-side row-sparse Adagrad;
    same contract as ``sharded_push_adagrad``.

    The (ids, grads) pair of each slot rides the ring so every shard
    sees every slot's gradients exactly once, holding only one [B, D]
    buffer; owners fold rows into a local accumulator as pairs pass.
    """
    from dgl_operator_tpu.obs.comm import register_collective

    ax = spec.axis
    n = spec.num_shards
    me = jax.lax.axis_index(ax)
    rps = spec.rows_per_shard
    # n-1 hops, each moving the (pids, pg) pair
    register_collective(
        "ring_push", ax,
        (n - 1) * (ids.shape[0] * 4
                   + grads.shape[0] * grads.shape[-1]
                   * grads.dtype.itemsize))

    def fold(carry, pair):
        acc, cnt = carry
        pids, pg = pair
        owner, local = _owner_and_local(jnp.maximum(pids, 0), spec)
        mine = (owner == me) & (pids >= 0)
        lidx = jnp.where(mine, local, rps)          # spare slot
        acc = acc + jax.ops.segment_sum(
            jnp.where(mine[:, None], pg, 0.0), lidx,
            num_segments=rps + 1)[:-1]
        cnt = cnt + jax.ops.segment_sum(
            mine.astype(jnp.float32), lidx, num_segments=rps + 1)[:-1]
        return (acc, cnt)

    acc0 = jnp.zeros_like(table)
    cnt0 = jnp.zeros((rps,), jnp.float32)
    carry = fold((acc0, cnt0), (ids, grads))

    def hop(c, _):
        carry, pids, pg = c
        pids = jax.lax.ppermute(pids, ax, _ring_perm(n))
        pg = jax.lax.ppermute(pg, ax, _ring_perm(n))
        carry = fold(carry, (pids, pg))
        return (carry, pids, pg), ()

    (carry, _, _), _ = jax.lax.scan(
        hop, (carry, ids, grads), jnp.arange(1, n))
    acc, cnt = carry
    touched = cnt > 0
    gsum = jnp.mean(acc * acc, axis=-1)
    new_state = state + jnp.where(touched, gsum, 0.0)
    step = acc * (lr / jnp.sqrt(new_state + eps))[:, None]
    new_table = table - jnp.where(touched[:, None], step, 0.0)
    return new_table, new_state


def make_ring_embedding_ops(mesh, spec: ShardedTableSpec):
    """Jitted shard_map bindings, signature-compatible with
    ``embedding.make_embedding_ops`` (shared binding contract)."""
    from dgl_operator_tpu.parallel.embedding import bind_embedding_ops

    return bind_embedding_ops(mesh, spec, ring_lookup,
                              ring_push_adagrad)
