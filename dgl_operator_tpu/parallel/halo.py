"""Owner-sharded halo feature exchange over the dp mesh.

The reference's DistGraph stores every node's features exactly once, on
the machine that owns the node, and trainers pull remote rows on demand
through the KVStore (DGL paper; dis_kvstore.py PULL). Our DistTrainer
historically replicated each partition's one-hop halo *into* its device
shard instead — simple, zero per-step traffic, but at products scale
halo rows run ~5x the inner core (benchmarks/SCALE_FULL.json
``halo_frac_of_inner``), so per-chip feature HBM barely drops as
partitions are added.

This module restores the owner-only storage model as in-program
collectives (``TrainConfig.feats_layout="owner"``): each mesh slot
stores just its core rows ``[c_pad, D]``, and remote rows move over ICI
inside the jitted step, against the halo ownership manifest the
partitioner emits (``halo_owner_part`` / ``halo_owner_local``,
graph/partition.py). Two exchange forms, chosen by access pattern:

- :func:`halo_row_lookup` — on-demand rows for a minibatch's input
  nodes (the training step): all_gather the per-slot request manifests
  (ints, ~D× smaller than rows), every owner contributes its rows with
  one masked local take, and a psum_scatter returns each slot exactly
  its ``[B, D]`` block — the same collective pair as the KVStore-
  replacement embedding pull (parallel/embedding.py), with ownership
  given *explicitly* per row instead of by blocked id arithmetic.
- :func:`halo_all_to_all` — the whole halo at once (layer-wise eval):
  per-(owner, receiver) send/recv index tables are precomputed on the
  host (:func:`build_exchange_tables`), so one ``all_to_all`` moves
  only pair-padded halo rows. This replaces eval's former global
  ``[N, D]`` psum buffer, whose bytes scaled with the FULL graph.

Everything is static-shape: manifests are padded to the mesh-wide halo
max with owner ``-1`` (no owner claims the row -> zeros, masked
downstream), exactly the padding discipline of the sampled minibatch
path. The host-sampler training exchange no longer runs inside the
train step at all: ``runtime/forward.build_halo_exchange_fn`` wraps the
compacted a2a into a standalone jitted stage the trainer dispatches one
batch ahead of compute (:func:`staging_buffer_bytes` is its HBM bill). Collective cost is accounted analytically by
:func:`exchange_bytes_per_step` (ring) and
:func:`alltoall_bytes_per_step` (compacted a2a) — the numbers surfaced
through runtime/timers.py byte counters and the scale bench's
``hbm_budget``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# default fraction of (padded) halo rows each slot keeps resident as a
# static cache (TrainConfig.halo_cache_frac): input features never
# change during training, so the hottest halo rows — sampling draws a
# halo node with probability proportional to its local edge count — are
# fetched once at load time instead of every step. Degree skew makes a
# small cache absorb an outsized share of requests (measured on the
# products-shaped bench partition: 25% of rows -> ~55% of requests).
DEFAULT_HALO_CACHE_FRAC = 0.25


def build_halo_cache(src: np.ndarray, num_nodes: int, num_inner: int,
                     cache_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """Degree-ranked hot-halo cache selection for ONE partition —
    standalone so the serving engine (serve/engine.py) and future
    autotune sweeps can build the cache without instantiating a
    trainer (it used to live inline in ``DistTrainer.__init__``).

    Hotness = local edge count: the neighbor sampler draws a halo node
    with probability proportional to the edges that reference it, so
    caching by local degree maximizes the request mass absorbed.

    src       : [num_edges] local src endpoint of every local edge.
    num_nodes : local node count ([core | halo] ordering).
    num_inner : core prefix length; halo rows follow.
    cache_rows: slots to fill (``round(halo_cache_frac * h_pad)``).

    Returns ``(cache_idx, slot_of)``:

    - ``cache_idx`` [cache_rows] halo-local rows to store, hottest
      first (a halo shorter than the cache repeats its hottest row so
      the slot count stays static); empty when the partition has no
      halo or the cache is disabled;
    - ``slot_of`` [num_halo] halo-local row -> cache slot, -1 = not
      cached. On padding duplicates the FIRST slot wins (reversed
      assign), matching the trainer's historical layout exactly.
    """
    nh = int(num_nodes) - int(num_inner)
    slot_of = np.full(max(nh, 0), -1, np.int32)
    if cache_rows <= 0 or nh <= 0:
        return np.zeros(0, np.int64), slot_of
    deg = np.bincount(np.asarray(src), minlength=num_nodes)[num_inner:]
    idx = np.argsort(-deg, kind="stable")[:cache_rows]
    if len(idx) < cache_rows:   # short halo: repeat hottest row
        idx = np.concatenate(
            [idx, np.repeat(idx[:1], cache_rows - len(idx))])
    slot_of[idx[::-1]] = np.arange(cache_rows - 1, -1, -1)
    return idx.astype(np.int64), slot_of


def halo_row_lookup(core_feats, owner, local, axis: str):
    """Collective on-demand row fetch over a ``ppermute`` ring. Runs
    *inside* shard_map over ``axis`` (one call per mesh slot).

    core_feats : [c_pad, D] this slot's owner-only feature shard.
    owner      : [B] int32 owning mesh slot per requested row
                 (-1 = padded request -> zero row).
    local      : [B] int32 row inside the owner's shard.
    returns [B, D] rows in the shard's dtype (bf16 tables exchange
    bf16 bytes; callers choose the compute dtype).

    Shape: the request manifests are all_gathered once (ints, ~D×
    smaller than rows), then each slot's [B, D] answer accumulator
    rides the ring — every owner adds the rows it holds as the
    accumulator passes (the ``parallel.ring`` pull pattern, with
    ownership explicit per row instead of blocked id arithmetic). On
    ICI this is byte-identical to a reduce-scatter (which IS a ring);
    as an explicit ring it also keeps the per-hop live buffer at
    O(B·D) on backends whose reduce-scatter materializes the full
    [nslots·B, D] image (XLA:CPU — measured 2× step cost on the
    virtual mesh).

    Rows this slot owns (``owner == axis_index``) ride the same ring
    as remote ones — a data-dependent local/remote split would need
    dynamic shapes, and the uniform exchange overlaps with compute
    either way.
    """
    from dgl_operator_tpu.obs.comm import register_collective
    from dgl_operator_tpu.parallel.mesh import body_axis_size

    me = jax.lax.axis_index(axis)
    n = body_axis_size(axis)
    # trace-time comm-ledger record: this seam's analytic bytes come
    # from the same model the scale bench bills (a ledger append only —
    # traced code must not emit telemetry, tpu-lint TPU001)
    register_collective(
        "halo_ring", axis,
        exchange_bytes_per_step(n, int(owner.shape[0]),
                                int(core_feats.shape[-1]),
                                core_feats.dtype.itemsize))
    # every owner sees every slot's request list: [nslots, B] (cheap)
    all_owner = jax.lax.all_gather(owner, axis)
    all_local = jax.lax.all_gather(local, axis)
    perm = [(s, (s + 1) % n) for s in range(n)]

    def contribution(slot):
        mine = all_owner[slot] == me
        rows = jnp.take(core_feats,
                        jnp.where(mine, all_local[slot], 0), axis=0)
        return jnp.where(mine[:, None], rows,
                         jnp.zeros((), rows.dtype))

    # at hop t the accumulator passing through slot m belongs to slot
    # (m - 1 - t) mod n; after n-1 hops it lands on its requester with
    # every owner's rows folded in (each row has exactly one owner, or
    # none for -1 pads -> zeros)
    acc = contribution((me - 1) % n)

    def hop(acc, t):
        acc = jax.lax.ppermute(acc, axis, perm)
        return acc + contribution((me - 1 - t) % n), ()

    if n > 1:
        acc, _ = jax.lax.scan(hop, acc, jnp.arange(1, n))
    return acc


def alltoall_serve_rows(core_feats, serve_rows, axis: str):
    """Compacted halo payload exchange, host-precomputed serve tables:
    ONE ``all_to_all`` — each requested row crosses ICI exactly once,
    instead of riding the whole ring like :func:`halo_row_lookup`'s
    uniform [B, D] accumulator (the form device-side sampling must
    use, since its requests only exist on device). Runs *inside*
    shard_map over ``axis``.

    The single-controller host sampler sees every slot's requests, so
    it hands each slot the transposed view directly: ``serve_rows``
    [P, pair_cap] are the owner-local rows THIS slot ships to each
    peer, ordered by the peer's request list (-1 pads ship a junk row
    the receiver's out-of-bounds scatter position drops). Returns
    ``recv`` [P, pair_cap, D]: ``recv[o, j]`` = the row owner *o*
    answered for this slot's j-th request to it — scatter it with the
    matching ``recv_pos`` table (:func:`build_request_tables`).
    """
    from dgl_operator_tpu.obs.comm import register_collective

    P, pair_cap = serve_rows.shape
    D = core_feats.shape[-1]
    # payload-only bill: the serve tables never cross the wire in this
    # form (the host precomputed them), unlike the request-first a2a
    register_collective(
        "halo_a2a_serve", axis,
        int(P) * int(pair_cap) * int(D) * core_feats.dtype.itemsize)
    served = jnp.take(core_feats, jnp.maximum(serve_rows, 0), axis=0)
    return jax.lax.all_to_all(served, axis, split_axis=0,
                              concat_axis=0, tiled=True)


def alltoall_request_rows(core_feats, req_rows, axis: str):
    """Compacted halo payload exchange for MULTI-controller runs: the
    host only sampled its own slots' batches, so the serve view can't
    be precomputed — a first (int-sized) ``all_to_all`` ships each
    slot's request tables to the owners, then the payload a2a answers
    them. Same return contract as :func:`alltoall_serve_rows`.

    req_rows : [P, pair_cap] int32 owner-local rows this slot asks
               each peer for (-1 pad -> junk row the receiver drops).
    """
    from dgl_operator_tpu.obs.comm import register_collective

    P, pair_cap = req_rows.shape
    register_collective(
        "halo_a2a_request", axis,
        alltoall_bytes_per_step(int(P), int(pair_cap),
                                int(core_feats.shape[-1]),
                                core_feats.dtype.itemsize))
    peer_req = jax.lax.all_to_all(req_rows, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
    served = jnp.take(core_feats, jnp.maximum(peer_req, 0), axis=0)
    return jax.lax.all_to_all(served, axis, split_axis=0,
                              concat_axis=0, tiled=True)


def halo_exchange_start(core_feats, ebatch, axis: str):
    """Issue ONE compacted halo payload exchange — the collective half
    of the owner-layout gather, dispatched by whichever request-table
    form ``ebatch`` carries (``exch_serve``: single-controller
    precomputed serve tables; ``exch_req``: the multi-controller
    request-first form). Runs *inside* shard_map over ``axis``.

    This is the single owner of that dispatch: the two-program
    prefetch stage (runtime/forward.build_halo_exchange_fn) and the
    fused in-program pipeline (parallel/dp.py ``fused_exchange``) both
    call it, so the staged and fused forms cannot drift.

    Named ``_start`` because in the fused form this is the START half
    of an async collective pair: the returned in-flight ``recv``
    handle must not be consumed until :func:`halo_exchange_done` pins
    it behind the step's compute — consuming it immediately (start
    directly followed by done) serializes the collective against the
    MXU work and defeats the overlap (tpu-lint TPU002 flags that
    shape). XLA's latency-hiding scheduler turns the independent
    collective subgraph into an async start it can issue under the
    compute; on backends without async collectives (XLA:CPU) the pair
    degrades to a plain in-program exchange with identical math.
    """
    if "exch_serve" in ebatch:
        return alltoall_serve_rows(core_feats, ebatch["exch_serve"],
                                   axis)
    return alltoall_request_rows(core_feats, ebatch["exch_req"], axis)


def halo_exchange_done(handle, anchor):
    """The DONE half of the fused async exchange: join the in-flight
    ``recv`` handle from :func:`halo_exchange_start` with ``anchor`` —
    a value the step's compute produces (the loss) — through one
    ``optimization_barrier``, and return ``(recv, anchor)``.

    The barrier makes both outputs depend on both inputs: the
    materialized recv cannot be consumed before the compute that
    produced ``anchor`` finishes (XLA cannot sink the done next to the
    start), and the collective cannot be dead-code-eliminated or
    hoisted past the join. The compute and the collective stay
    INDEPENDENT subgraphs up to this point, which is exactly what lets
    the scheduler run the exchange under the matmul/aggregation work.
    """
    handle, anchor = jax.lax.optimization_barrier((handle, anchor))
    return handle, anchor


def build_exchange_tables(owner: np.ndarray, local: np.ndarray
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side pair tables for :func:`halo_all_to_all`.

    owner/local : [P, h_pad] int32 global halo manifests (owner -1 on
    padded rows), part-major — slot r's halo row j is owned by
    ``owner[r, j]`` at that owner's core row ``local[r, j]``.

    Returns ``(send_local, recv_slot)``, both ``[P, P, pair_pad]``:

    - ``send_local[o, r]`` — core rows slot *o* ships to receiver *r*
      (pad -> row 0; the receiver never lands pads anywhere real);
    - ``recv_slot[r, o]`` — halo-buffer position where the row arriving
      from owner *o* lands at receiver *r* (pad -> ``h_pad``, the
      scatter's dummy row).

    Both are dp-shardable on their leading axis: the all_to_all runs
    each slot against ITS row of each table.
    """
    P, h_pad = owner.shape
    counts = np.zeros((P, P), dtype=np.int64)
    for r in range(P):
        v = owner[r][owner[r] >= 0]
        counts[r] += np.bincount(v, minlength=P)
    pair_pad = max(1, int(counts.max()))
    send_local = np.zeros((P, P, pair_pad), np.int32)
    recv_slot = np.full((P, P, pair_pad), h_pad, np.int32)
    for r in range(P):
        for o in range(P):
            sel = np.nonzero(owner[r] == o)[0]
            send_local[o, r, :len(sel)] = local[r, sel]
            recv_slot[r, o, :len(sel)] = sel
    return send_local, recv_slot


def halo_all_to_all(core_feats, send_local, recv_slot, h_pad: int,
                    axis: str):
    """Whole-halo exchange. Runs *inside* shard_map over ``axis``.

    core_feats : [c_pad, D] this slot's owner-only shard.
    send_local : [P, pair_pad] this slot's send table
                 (``build_exchange_tables`` row, dp-sharded).
    recv_slot  : [P, pair_pad] this slot's receive table.
    returns [h_pad, D] — this slot's halo rows, in shard order (padded
    rows zero).

    One tiled ``all_to_all`` moves only pair-padded halo rows — at
    8 parts roughly ``max_pair/h_pad`` of what a naive all_gather of
    whole shards would, and independent of the full graph size the old
    eval psum paid.
    """
    from dgl_operator_tpu.obs.comm import register_collective

    D = core_feats.shape[-1]
    P, pair_pad = send_local.shape
    register_collective(
        "halo_a2a_full", axis,
        int(P) * int(pair_pad) * int(D) * core_feats.dtype.itemsize)
    send = jnp.take(core_feats, send_local, axis=0)   # [P, pair, D]
    recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    # recv[o, j] = the row owner o shipped for my recv_slot[o, j]
    buf = jnp.zeros((h_pad + 1, D), core_feats.dtype)
    buf = buf.at[recv_slot.reshape(-1)].set(recv.reshape(-1, D))
    return buf[:h_pad]


def exchange_bytes_per_step(num_slots: int, rows: int, feat_dim: int,
                            itemsize: int = 4) -> int:
    """Analytic per-slot ICI bytes of one :func:`halo_row_lookup`:
    the request all_gather (owner + local, int32 each, from every
    slot) plus the ring that returns the row payload. This module owns
    both exchange-cost models (ring here, compacted a2a in
    :func:`alltoall_bytes_per_step`) — consumed by the trainer's byte
    counters (runtime/timers.py) and the scale bench's ``hbm_budget``
    so the two can't drift apart."""
    request = num_slots * rows * 2 * 4
    payload = num_slots * rows * feat_dim * itemsize
    return request + payload


def staging_buffer_bytes(num_slots: int, pair_cap: int, feat_dim: int,
                         depth: int = 2, itemsize: int = 4) -> int:
    """Per-slot HBM bill of the decoupled halo prefetch stage
    (runtime/dist.py): the jitted exchange stage materializes each
    batch's a2a ``recv`` payload ``[num_slots, pair_cap, D]`` (storage
    dtype — only the COLLECTIVE is staged; the local take/scatter stay
    fused in the step) and keeps up to ``depth`` of them staged ahead
    of the consuming step. Donation of the staged buffer into the
    compute step is what caps the residency at ``depth`` + the one
    being consumed (the ``prefetch + 2`` bound in docs/design.md);
    without donation every in-flight batch would pin its own copy.
    Consumed by the scale bench's ``hbm_budget`` next to the exchange
    cost models above so the pipeline's memory story stays analytic."""
    return depth * num_slots * pair_cap * feat_dim * itemsize


def alltoall_bytes_per_step(num_slots: int, pair_cap: int,
                            feat_dim: int, itemsize: int = 4) -> int:
    """Analytic per-slot ICI bytes of one compacted a2a exchange
    (:func:`alltoall_serve_rows` / :func:`alltoall_request_rows`):
    the request a2a (int32 rows out) plus the payload a2a back —
    each requested row crosses once, so the bill scales with the
    calibrated pair caps, not the full input width."""
    return num_slots * pair_cap * (4 + feat_dim * itemsize)
