from dgl_operator_tpu.parallel.mesh import (  # noqa: F401
    DP_AXIS, MP_AXIS, make_mesh, make_mesh_2d, make_train_mesh,
    replicated, dp_sharded, shard_leading, axis_size, shard_map)
from dgl_operator_tpu.parallel.dp import (  # noqa: F401
    make_dp_train_step, make_dp_eval_step, stack_batches, replicate, dp_shard,
    param_allgather_start, param_allgather_done)
from dgl_operator_tpu.parallel.shardrules import (  # noqa: F401
    match_partition_rules, opt_state_specs, place_by_specs, to_pspec,
    sharding_summary, emit_state_gauges)
from dgl_operator_tpu.parallel.embedding import (  # noqa: F401
    ShardedTableSpec, init_table, make_embedding_ops, sharded_lookup,
    sharded_push_adagrad, dense_push_adagrad)
from dgl_operator_tpu.parallel.halo import (  # noqa: F401
    halo_row_lookup, halo_all_to_all, build_exchange_tables,
    exchange_bytes_per_step)
from dgl_operator_tpu.parallel.bootstrap import (  # noqa: F401
    parse_hostfile, initialize_from_hostfile, write_hostfile, revise_hostfile,
    HostEntry)
from dgl_operator_tpu.parallel.ring_attention import (  # noqa: F401
    ring_dot_attention, ring_gat_attention, dense_dot_attention,
    dense_gat_attention, make_ring_attention)
