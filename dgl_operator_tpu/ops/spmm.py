"""gspmm — generalized sparse-matrix message passing (gather + segment).

Equivalent capability to DGL's ``update_all(message_fn, reduce_fn)``
pipeline that the reference's models drive from Python (hand-written
message passing: examples/GraphSAGE/code/3_message_passing.py:85-141).
On TPU this is: gather source rows (XLA dynamic-gather, contiguous in
HBM), elementwise-combine with edge data (fused by XLA), segment-reduce
into destination rows.

Inputs use the ``DeviceGraph`` layout: edges sorted by dst, padded edges
pointing at dummy segment ``num_nodes``.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from dgl_operator_tpu.graph.graph import DeviceGraph
from dgl_operator_tpu.ops import segment as seg

_BINARY = {
    "copy_u": lambda u, e: u,
    "copy_e": lambda u, e: e,
    "u_mul_e": lambda u, e: u * e,
    "u_add_e": lambda u, e: u + e,
    "u_sub_e": lambda u, e: u - e,
    "u_div_e": lambda u, e: u / e,
    # reversed non-commutative forms (DGL names both orders; the
    # commutative ones alias the u_*_e spellings above)
    "e_sub_u": lambda u, e: e - u,
    "e_div_u": lambda u, e: e / u,
}
_REDUCE = {"sum", "mean", "max", "min"}


def gspmm(g: DeviceGraph, op: str, reduce: str, ufeat=None, efeat=None):
    """Message passing: ``out[v] = reduce_{(u,v) in E} op(ufeat[u], efeat[uv])``.

    ufeat: [num_nodes, ...]; efeat: [num_edges, ...] already in the
    graph's (dst-sorted, padded) edge order — use
    ``DeviceGraph.permute_edata`` when staging host features.
    Returns [num_nodes, ...].
    """
    if op not in _BINARY:
        raise ValueError(f"unknown message op {op}")
    if reduce not in _REDUCE:
        raise ValueError(f"unknown reduce {reduce}")
    u = ufeat[g.src] if ufeat is not None else None
    msg = _BINARY[op](u, efeat)
    # broadcast edge mask over trailing dims; padded edges already point
    # at the spare segment, masking additionally protects max-reduce
    nseg = g.num_nodes + 1
    dst = jnp.asarray(g.dst)
    srt = g.sorted_by_dst
    if reduce == "sum":
        out = seg.segment_sum(msg, dst, nseg, sorted=srt)
    elif reduce == "mean":
        out = seg.segment_mean(msg, dst, nseg, sorted=srt)
    else:
        # max/min: mask padded edges to the reduce's identity so they
        # can never win, then zero empty segments (DGL convention).
        # Integer features keep their dtype (DGL parity): the identity
        # is the dtype's own extreme, not +/-inf (which would promote)
        mask = jnp.asarray(g.edge_mask).reshape((-1,) + (1,) * (msg.ndim - 1))
        if jnp.issubdtype(msg.dtype, jnp.floating):
            ident = jnp.asarray(-jnp.inf if reduce == "max" else jnp.inf,
                                dtype=msg.dtype)
        else:
            info = jnp.iinfo(msg.dtype)
            ident = jnp.asarray(info.min if reduce == "max" else info.max,
                                dtype=msg.dtype)
        msg = jnp.where(mask > 0, msg, ident)
        fn = seg.segment_max if reduce == "max" else seg.segment_min
        out = fn(msg, dst, nseg, sorted=srt)
        # Zero empty segments by counting real edges per segment rather
        # than comparing the reduced value to the masking identity — a
        # genuine message equal to iinfo.max/min (or +/-inf) must survive
        count = seg.segment_sum(
            jnp.asarray(g.edge_mask, jnp.int32), dst, nseg, sorted=srt
        )
        count = count.reshape(count.shape + (1,) * (out.ndim - 1))
        out = jnp.where(count > 0, out, jnp.zeros((), out.dtype))
    return out[: g.num_nodes]


copy_u_sum = partial(gspmm, op="copy_u", reduce="sum")
copy_u_mean = partial(gspmm, op="copy_u", reduce="mean")
copy_u_max = partial(gspmm, op="copy_u", reduce="max")
