"""Pallas TPU kernels for the irregular-memory hot ops.

The two ops that dominate sampled GNN training are row gathers out of
HBM (feature loading — reference ``load_subtensor``,
examples/GraphSAGE_dist/code/train_dist.py:45-49) and fanout
aggregation (neighbor mean — SAGEConv message passing, DGL's CUDA SpMM
in the reference). XLA implements both as gather HLOs that materialize
the full ``[rows, D]`` / ``[num_dst, fanout, D]`` intermediate in HBM:
the fanout path pays ``3·E·D`` HBM traffic (gather write + reduce
read + output). These kernels fuse gather and reduce — each source row
is DMA'd HBM→VMEM exactly once and reduced on-chip, cutting traffic to
``E·D + N·D`` — with manually double-buffered row DMAs so transfers
overlap the reduction (pallas_guide: Async DMA / Double Buffering).

Layout: Mosaic only allows arbitrary-offset DMA slicing along UNTILED
leading dimensions, so tables are viewed as ``[N, 1, D]`` — dim 0 is
untiled (sliceable per row), the (1, D) tail is the tiled part. Row
width must be lane-aligned (``D % 128 == 0``); :func:`supported` gates
dispatch and other widths take the XLA path.

Invalid-slot convention: callers redirect masked-out neighbor slots to
a spare all-zero row appended to the table, so the kernels are pure
gather+sum with no in-kernel masking (branch-free inner loop).

Gradients: forward is Pallas; backward is the mathematical transpose —
a scatter-add — expressed as an XLA ``segment_sum``, exactly what XLA
emits for a native gather's VJP, so training pays nothing extra.

Works in interpreter mode on CPU (tests) and compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _compiler_params(**kw):
    """TPU compiler params across the CompilerParams rename: bind the
    dataclass this jax ships and drop fields it predates (0.4.x has no
    ``has_side_effects`` — these kernels' outputs are always consumed,
    so DCE protection is advisory there)."""
    import dataclasses

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in names})

_LANE = 128
# rows handled per grid step; also the number of in-flight row DMAs for
# the flat gather
_GATHER_TILE = 32
_FANOUT_TILE = 8
_NBUF = 2  # double buffer


def supported(d: int) -> bool:
    """Kernel constraint: row width must be lane-aligned."""
    return d % _LANE == 0


def _pad_rows(n: int, tile: int) -> int:
    return ((n + tile - 1) // tile) * tile


# --------------------------------------------------------------------------
# flat row gather: out[i] = table[idx[i]]
# --------------------------------------------------------------------------

def _gather_kernel(idx_ref, table_ref, out_ref, sems, *, tile: int):
    base = pl.program_id(0) * tile

    def row_dma(t):
        return pltpu.make_async_copy(
            table_ref.at[idx_ref[base + t]], out_ref.at[t], sems.at[t])

    def start(t, _):
        row_dma(t).start()
        return 0

    jax.lax.fori_loop(0, tile, start, 0)

    def wait(t, _):
        row_dma(t).wait()
        return 0

    jax.lax.fori_loop(0, tile, wait, 0)


def _gather_rows_fwd_impl(table, idx, *, interpret: bool):
    rows, d = table.shape
    if not supported(d):
        return jnp.take(table, idx, axis=0)
    (m,) = idx.shape
    m_pad = _pad_rows(max(m, 1), _GATHER_TILE)
    idx_pad = jnp.pad(idx, (0, m_pad - m))  # pad rows read table row 0
    out = pl.pallas_call(
        functools.partial(_gather_kernel, tile=_GATHER_TILE),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m_pad // _GATHER_TILE,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(
                (_GATHER_TILE, 1, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((_GATHER_TILE,))],
        ),
        out_shape=jax.ShapeDtypeStruct((m_pad, 1, d), table.dtype),
        compiler_params=_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(idx_pad.astype(jnp.int32), table.reshape(rows, 1, d))
    return out.reshape(m_pad, d)[:m]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gather_rows_pallas(table, idx, interpret: bool = False):
    """``table[idx]`` with fused DMA pipelining. table: [N, D]; idx: [M]."""
    return _gather_rows_fwd_impl(table, idx, interpret=interpret)


def _gather_rows_fwd(table, idx, interpret):
    return _gather_rows_fwd_impl(table, idx, interpret=interpret), \
        (idx, table.shape[0])


def _gather_rows_bwd(interpret, res, g):
    idx, n = res
    # transpose of a gather = scatter-add (XLA segment_sum, like the
    # native gather VJP)
    dt = jax.ops.segment_sum(g, idx, num_segments=n)
    return (dt.astype(g.dtype), None)


gather_rows_pallas.defvjp(_gather_rows_fwd, _gather_rows_bwd)


# --------------------------------------------------------------------------
# fused fanout gather+sum: out[i] = sum_k table[nbr[i, k]]
# --------------------------------------------------------------------------

def _fanout_kernel(nbr_ref, table_ref, out_ref, scratch, sems,
                   *, tile: int, fanout: int):
    base = pl.program_id(0) * tile

    def row_dma(slot, r, k):
        return pltpu.make_async_copy(
            table_ref.at[nbr_ref[base + r, k]],
            scratch.at[slot, k], sems.at[slot, k])

    def start_row(r):
        slot = r % _NBUF

        def body(k, _):
            row_dma(slot, r, k).start()
            return 0

        jax.lax.fori_loop(0, fanout, body, 0)

    start_row(0)

    def row_body(r, _):
        slot = r % _NBUF

        @pl.when(r + 1 < tile)
        def _():
            start_row(r + 1)

        def wait_body(k, _):
            row_dma(slot, r, k).wait()
            return 0

        jax.lax.fori_loop(0, fanout, wait_body, 0)

        def acc_body(k, acc):
            return acc + scratch[slot, k].astype(jnp.float32)

        acc = jax.lax.fori_loop(
            0, fanout, acc_body,
            jnp.zeros(scratch.shape[2:], jnp.float32))
        out_ref[pl.ds(r, 1)] = acc.astype(out_ref.dtype)[None]
        return 0

    jax.lax.fori_loop(0, tile, row_body, 0)


def _fanout_sum_fwd_impl(table, nbr, *, interpret: bool):
    rows, d = table.shape
    nd, f = nbr.shape
    if not supported(d):
        return jnp.take(table, nbr, axis=0).sum(axis=1)
    nd_pad = _pad_rows(max(nd, 1), _FANOUT_TILE)
    # pad rows gather the spare zero row (last table row by convention)
    nbr_pad = jnp.pad(nbr, ((0, nd_pad - nd), (0, 0)),
                      constant_values=rows - 1)
    out = pl.pallas_call(
        functools.partial(_fanout_kernel, tile=_FANOUT_TILE, fanout=f),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nd_pad // _FANOUT_TILE,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(
                (_FANOUT_TILE, 1, d), lambda i, *_: (i, 0, 0),
                memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((_NBUF, f, 1, d), table.dtype),
                pltpu.SemaphoreType.DMA((_NBUF, f)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((nd_pad, 1, d), table.dtype),
        compiler_params=_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(nbr_pad.astype(jnp.int32), table.reshape(rows, 1, d))
    return out.reshape(nd_pad, d)[:nd]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fanout_sum_pallas(table, nbr, interpret: bool = False):
    """``sum_k table[nbr[:, k]]`` fused in one HBM pass.

    ``table``: [N, D] with a spare all-zero LAST row; ``nbr``: [ND, F]
    int32 where masked-out slots point at that spare row."""
    return _fanout_sum_fwd_impl(table, nbr, interpret=interpret)


def _fanout_sum_fwd(table, nbr, interpret):
    return _fanout_sum_fwd_impl(table, nbr, interpret=interpret), \
        (nbr, table.shape[0])


def _fanout_sum_bwd(interpret, res, g):
    nbr, n = res
    nd, f = nbr.shape
    d = g.shape[-1]
    ge = jnp.broadcast_to(g[:, None, :], (nd, f, d)).reshape(nd * f, d)
    dt = jax.ops.segment_sum(ge, nbr.reshape(-1), num_segments=n)
    return (dt.astype(g.dtype), None)


fanout_sum_pallas.defvjp(_fanout_sum_fwd, _fanout_sum_bwd)


# --------------------------------------------------------------------------
# numpy reference implementations (tests)
# --------------------------------------------------------------------------

def gather_rows_reference(table: np.ndarray, idx: np.ndarray) -> np.ndarray:
    return np.asarray(table)[np.asarray(idx)]


def fanout_sum_reference(table: np.ndarray, nbr: np.ndarray) -> np.ndarray:
    return np.asarray(table)[np.asarray(nbr)].sum(axis=1)
