"""Dense fixed-fanout aggregation — the sampled-path hot loop on TPU.

The reference's sampled training aggregates ragged neighbor sets through
DGL blocks (examples/GraphSAGE_dist/code/train_dist.py:52-70). The
TPU-native form avoids ragged data entirely: neighbors live in a dense
``[num_dst, fanout]`` table (``FanoutBlock``), so aggregation is

    gather [num_dst, fanout, D]  ->  masked reduce over axis 1

with fully static shapes and no scatter/segment ids.

Two execution paths, selected by :func:`use_pallas`:

- **XLA** (the current default everywhere, including TPU): dense gather
  + masked reduce; XLA fuses the reduce into the following matmul but
  materializes the gathered ``[num_dst, fanout, D]`` intermediate in
  HBM.
- **Pallas** (opt-in): the fused gather+sum kernels in
  ``ops.pallas_gather`` — each source row crosses HBM once. Masking is
  folded into the index table (invalid slots -> spare zero row), the
  mean's count division happens outside the kernel on ``[num_dst]``
  vectors. Requires lane-aligned rows (``D % 128 == 0``).

``DGL_TPU_PALLAS`` selects: ``1`` forces the kernels (compiled),
``interpret`` forces interpreter mode (how the CPU test suite
exercises the kernel code path), ``0`` forces XLA, and the default
``auto`` consults the recorded on-hardware kernel benchmark
(benchmarks/KERNELS_TPU.json, written by bench.py on a real TPU):
Pallas wins the benchmark -> Pallas on TPU; no data or XLA wins ->
XLA. The default is decided by measurement, never by guess.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from dgl_operator_tpu.graph.blocks import FanoutBlock
from dgl_operator_tpu.ops import pallas_gather as _pg


_KERNEL_RECORD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "KERNELS_TPU.json")
_auto_cache: dict = {}


def _auto_default() -> bool:
    """Data-driven default (VERDICT r2 item 4): "auto" consults the
    recorded on-hardware kernel benchmark (written by bench.py's
    bench_kernels when it runs on a real TPU). Pallas is enabled only
    when (a) this process is on a TPU backend and (b) the recorded
    benchmark measured the Pallas kernels faster than the XLA path.
    No record, or a record that says XLA wins -> XLA. Never guesses.
    """
    if "v" in _auto_cache:
        return _auto_cache["v"]
    result = False
    try:
        import jax
        if jax.default_backend() == "tpu":
            import json
            with open(_KERNEL_RECORD) as f:
                result = json.load(f).get("recommendation") == "pallas"
    except Exception:  # noqa: BLE001 — no record / no backend = XLA
        result = False
    _auto_cache["v"] = result
    return result


def use_pallas() -> bool:
    """Whole-backend dispatch default (the legacy seam): env override,
    else the recorded KERNELS_TPU.json recommendation on TPU. The hot
    ops below refine this PER SHAPE through :func:`dispatch_pallas`."""
    mode = os.environ.get("DGL_TPU_PALLAS", "auto")
    if mode in ("1", "interpret"):
        return True
    if mode == "auto":
        return _auto_default()
    return False


def dispatch_pallas(rows: int, d: int, fanout: "int | None" = None
                    ) -> bool:
    """Shape-aware kernel dispatch (ISSUE 14): explicit env settings
    win as ever; under "auto" on a TPU backend the decision comes from
    the measured per-(rows, D, fanout) table ``benchmarks/KERNELS.json``
    (ops/dispatch.py — a shape whose Pallas arm failed to compile is
    retired to XLA by its own record), falling back to the legacy
    whole-backend KERNELS_TPU.json recommendation when no per-shape
    table exists. Never guesses."""
    mode = os.environ.get("DGL_TPU_PALLAS", "auto")
    if mode in ("1", "interpret"):
        return True
    if mode != "auto":
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # noqa: BLE001 — no backend: XLA
        return False
    from dgl_operator_tpu.ops import dispatch
    rec = dispatch.recommend(rows, d, fanout)
    if rec is None:
        return _auto_default()
    return rec == "pallas"


def _interpret() -> bool:
    return os.environ.get("DGL_TPU_PALLAS") == "interpret"


def gather_rows(table, idx):
    """``table[idx]`` — feature loading (load_subtensor parity,
    reference train_dist.py:45-49). Pallas-fused on TPU when the
    measured table says so for this shape."""
    idx = jnp.asarray(idx)
    if dispatch_pallas(int(idx.shape[0]) if idx.ndim else 1,
                       int(jnp.asarray(table).shape[-1])):
        return _pg.gather_rows_pallas(table, idx, _interpret())
    return jnp.asarray(table)[idx]


def _zero_padded(block: FanoutBlock, h_src):
    """Table with a spare zero row; invalid slots redirected to it."""
    h = jnp.asarray(h_src)
    table = jnp.concatenate([h, jnp.zeros((1, h.shape[-1]), h.dtype)])
    nbr = jnp.where(jnp.asarray(block.mask) > 0,
                    jnp.asarray(block.nbr), h.shape[0])
    return table, nbr.astype(jnp.int32)


def fanout_gather(block: FanoutBlock, h_src):
    """[num_dst, fanout, D] gathered neighbor features (invalid slots are
    whatever row 0 holds — always combine with the mask)."""
    return jnp.asarray(h_src)[block.nbr]


def _mask_f32(block: FanoutBlock):
    """Masks ship uint8 (pad_minibatch transport encoding) and re-widen
    here, on device, where the cast fuses into the consuming reduce."""
    return jnp.asarray(block.mask).astype(jnp.float32)


def fanout_sum(block: FanoutBlock, h_src):
    # check the kernel's lane-alignment constraint BEFORE building the
    # zero-padded table copy, or unsupported widths pay an O(N*D)
    # allocation only to fall back
    nd, f = jnp.asarray(block.nbr).shape
    if dispatch_pallas(int(nd), int(jnp.asarray(h_src).shape[-1]),
                       int(f)) \
            and _pg.supported(jnp.asarray(h_src).shape[-1]):
        table, nbr = _zero_padded(block, h_src)
        return _pg.fanout_sum_pallas(table, nbr, _interpret())
    m = _mask_f32(block)[..., None]
    return (fanout_gather(block, h_src) * m).sum(axis=1)


def fanout_mean(block: FanoutBlock, h_src):
    cnt = jnp.maximum(_mask_f32(block).sum(axis=1), 1.0)
    return fanout_sum(block, h_src) / cnt[:, None]


def fanout_max(block: FanoutBlock, h_src):
    m = _mask_f32(block)[..., None]
    x = fanout_gather(block, h_src)
    x = jnp.where(m > 0, x, -jnp.inf)
    out = x.max(axis=1)
    # rows with zero valid neighbors reduce to -inf -> 0, matching the
    # zero-in-degree convention of the segment path
    return jnp.where(jnp.isfinite(out), out, 0.0)
