"""Dense fixed-fanout aggregation — the sampled-path hot loop on TPU.

The reference's sampled training aggregates ragged neighbor sets through
DGL blocks (examples/GraphSAGE_dist/code/train_dist.py:52-70). The
TPU-native form avoids ragged data entirely: neighbors live in a dense
``[num_dst, fanout]`` table (``FanoutBlock``), so aggregation is

    gather [num_dst, fanout, D]  ->  masked reduce over axis 1

with fully static shapes and no scatter/segment ids.

Two execution paths, selected by :func:`use_pallas`:

- **XLA** (the current default everywhere, including TPU): dense gather
  + masked reduce; XLA fuses the reduce into the following matmul but
  materializes the gathered ``[num_dst, fanout, D]`` intermediate in
  HBM.
- **Pallas** (opt-in): the fused gather+sum kernels in
  ``ops.pallas_gather`` — each source row crosses HBM once. Masking is
  folded into the index table (invalid slots -> spare zero row), the
  mean's count division happens outside the kernel on ``[num_dst]``
  vectors. Requires lane-aligned rows (``D % 128 == 0``).

``DGL_TPU_PALLAS`` selects: ``1`` enables the kernels (compiled),
``interpret`` enables them in interpreter mode (how the CPU test suite
exercises the kernel code path), anything else — including the default
— takes the XLA path until on-hardware benchmarks justify flipping the
default (see use_pallas()).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from dgl_operator_tpu.graph.blocks import FanoutBlock
from dgl_operator_tpu.ops import pallas_gather as _pg


def use_pallas() -> bool:
    # Default "auto" currently resolves to the XLA path even on TPU:
    # the kernels are numerics-verified compiled (flat gather) and in
    # interpreter mode (both), but end-to-end compiled throughput has
    # not been benchmarked on hardware yet. Opt in with
    # DGL_TPU_PALLAS=1; flip the auto default once bench data lands.
    mode = os.environ.get("DGL_TPU_PALLAS", "auto")
    if mode in ("1", "interpret"):
        return True
    return False


def _interpret() -> bool:
    return os.environ.get("DGL_TPU_PALLAS") == "interpret"


def gather_rows(table, idx):
    """``table[idx]`` — feature loading (load_subtensor parity,
    reference train_dist.py:45-49). Pallas-fused on TPU."""
    if use_pallas():
        return _pg.gather_rows_pallas(table, jnp.asarray(idx),
                                      _interpret())
    return jnp.asarray(table)[jnp.asarray(idx)]


def _zero_padded(block: FanoutBlock, h_src):
    """Table with a spare zero row; invalid slots redirected to it."""
    h = jnp.asarray(h_src)
    table = jnp.concatenate([h, jnp.zeros((1, h.shape[-1]), h.dtype)])
    nbr = jnp.where(jnp.asarray(block.mask) > 0,
                    jnp.asarray(block.nbr), h.shape[0])
    return table, nbr.astype(jnp.int32)


def fanout_gather(block: FanoutBlock, h_src):
    """[num_dst, fanout, D] gathered neighbor features (invalid slots are
    whatever row 0 holds — always combine with the mask)."""
    return jnp.asarray(h_src)[block.nbr]


def fanout_sum(block: FanoutBlock, h_src):
    # check the kernel's lane-alignment constraint BEFORE building the
    # zero-padded table copy, or unsupported widths pay an O(N*D)
    # allocation only to fall back
    if use_pallas() and _pg.supported(jnp.asarray(h_src).shape[-1]):
        table, nbr = _zero_padded(block, h_src)
        return _pg.fanout_sum_pallas(table, nbr, _interpret())
    m = jnp.asarray(block.mask)[..., None]
    return (fanout_gather(block, h_src) * m).sum(axis=1)


def fanout_mean(block: FanoutBlock, h_src):
    cnt = jnp.maximum(jnp.asarray(block.mask).sum(axis=1), 1.0)
    return fanout_sum(block, h_src) / cnt[:, None]


def fanout_max(block: FanoutBlock, h_src):
    m = jnp.asarray(block.mask)[..., None]
    x = fanout_gather(block, h_src)
    x = jnp.where(m > 0, x, -jnp.inf)
    out = x.max(axis=1)
    # rows with zero valid neighbors reduce to -inf -> 0, matching the
    # zero-in-degree convention of the segment path
    return jnp.where(jnp.isfinite(out), out, 0.0)
