"""Dense fixed-fanout aggregation — the sampled-path hot loop on TPU.

The reference's sampled training aggregates ragged neighbor sets through
DGL blocks (examples/GraphSAGE_dist/code/train_dist.py:52-70). The
TPU-native form avoids ragged data entirely: neighbors live in a dense
``[num_dst, fanout]`` table (``FanoutBlock``), so aggregation is

    gather [num_dst, fanout, D]  ->  masked reduce over axis 1

which XLA fuses with the subsequent Linear into MXU work. No scatter, no
segment ids, fully static shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

from dgl_operator_tpu.graph.blocks import FanoutBlock


def fanout_gather(block: FanoutBlock, h_src):
    """[num_dst, fanout, D] gathered neighbor features (invalid slots are
    whatever row 0 holds — always combine with the mask)."""
    return jnp.asarray(h_src)[block.nbr]


def fanout_sum(block: FanoutBlock, h_src):
    m = jnp.asarray(block.mask)[..., None]
    return (fanout_gather(block, h_src) * m).sum(axis=1)


def fanout_mean(block: FanoutBlock, h_src):
    m = jnp.asarray(block.mask)[..., None]
    s = (fanout_gather(block, h_src) * m).sum(axis=1)
    cnt = jnp.maximum(m.sum(axis=1), 1.0)
    return s / cnt


def fanout_max(block: FanoutBlock, h_src):
    m = jnp.asarray(block.mask)[..., None]
    x = fanout_gather(block, h_src)
    x = jnp.where(m > 0, x, -jnp.inf)
    out = x.max(axis=1)
    # rows with zero valid neighbors reduce to -inf -> 0, matching the
    # zero-in-degree convention of the segment path
    return jnp.where(jnp.isfinite(out), out, 0.0)
