"""Segment reductions — the XLA replacement for DGL's CUDA SpMM.

DGL lowers ``update_all(copy_u, sum)`` to cusparse/CUDA SpMM kernels; the
idiomatic XLA form is a segment reduction over an edge array sorted by
destination (SURVEY.md §7). ``indices_are_sorted=True`` lets XLA emit the
fast path.

All functions take ``num_segments`` statically so results are
jit-stable. Padded edges must point at segment id ``num_segments`` and
callers allocate one spare row (see ``Graph.to_device``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments: int, sorted: bool = True):
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments,
                               indices_are_sorted=sorted)


def segment_mean(data, segment_ids, num_segments: int, sorted: bool = True):
    s = segment_sum(data, segment_ids, num_segments, sorted)
    ones = jnp.ones((data.shape[0],), dtype=data.dtype)
    cnt = segment_sum(ones, segment_ids, num_segments, sorted)
    cnt = jnp.maximum(cnt, 1.0)
    return s / cnt.reshape((-1,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments: int, sorted: bool = True):
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments,
                               indices_are_sorted=sorted)


def segment_min(data, segment_ids, num_segments: int, sorted: bool = True):
    return jax.ops.segment_min(data, segment_ids, num_segments=num_segments,
                               indices_are_sorted=sorted)


def segment_softmax(scores, segment_ids, num_segments: int, sorted: bool = True):
    """Numerically-stable softmax over edges grouped by destination —
    the attention normalizer for GAT (DGL's ``edge_softmax``)."""
    smax = segment_max(scores, segment_ids, num_segments, sorted)
    smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
    shifted = scores - smax[segment_ids]
    ex = jnp.exp(shifted)
    denom = segment_sum(ex, segment_ids, num_segments, sorted)
    denom = jnp.maximum(denom, 1e-16)
    return ex / denom[segment_ids]
