from dgl_operator_tpu.ops.segment import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_softmax)
from dgl_operator_tpu.ops.spmm import gspmm, copy_u_sum, copy_u_mean, copy_u_max  # noqa: F401
from dgl_operator_tpu.ops.sddmm import gsddmm, u_dot_v, u_add_v, u_sub_v  # noqa: F401
from dgl_operator_tpu.ops.fanout import (  # noqa: F401
    fanout_gather, fanout_mean, fanout_sum, fanout_max, gather_rows,
    use_pallas, dispatch_pallas)
