"""gsddmm — sampled dense-dense ops producing per-edge values.

Capability parity with DGL's ``apply_edges(fn.u_dot_v / u_add_v / ...)``
used by the reference for link-prediction scoring
(examples/GraphSAGE/code/4_link_predict.py:130-137 DotPredictor) and by
attention layers. On TPU: two row gathers + a fused elementwise/contraction,
all dense — XLA fuses the whole thing into one kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

from dgl_operator_tpu.graph.graph import DeviceGraph

_OPS = {
    "dot": lambda a, b: (a * b).sum(-1, keepdims=True),
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    # DGL's copy_lhs/copy_rhs: per-edge gather of one endpoint's rows
    "copy_u": lambda a, b: a,
    "copy_v": lambda a, b: b,
}


def gsddmm(g: DeviceGraph, op: str, ufeat, vfeat=None):
    """Per-edge ``op(ufeat[src], vfeat[dst])``; returns [num_edges, ...].

    The unused side of a copy op may be None and is never gathered
    (same convention as gspmm's optional ufeat/efeat)."""
    if op not in _OPS:
        raise ValueError(f"unknown sddmm op {op}")
    a = jnp.asarray(ufeat)[g.src] if op != "copy_v" else None
    b = jnp.asarray(vfeat)[g.dst] if op != "copy_u" else None
    return _OPS[op](a, b)


def u_dot_v(g: DeviceGraph, u, v):
    return gsddmm(g, "dot", u, v)


def u_add_v(g: DeviceGraph, u, v):
    return gsddmm(g, "add", u, v)


def u_sub_v(g: DeviceGraph, u, v):
    return gsddmm(g, "sub", u, v)
