"""On-device neighbor sampling — the TPU-native sampler.

The reference samples neighbors on host CPU in dedicated sampler
processes (launch.py num_samplers env protocol) because its aggregation
kernels live on the accelerator but its graph lives in host DGL
structures. On TPU that split is the bottleneck twice over: the host
sampler saturates one core long before the MXU is busy, and every
sampled minibatch must cross host->device. This module moves sampling
*into the compiled step*: the CSR graph (indptr + indices) is
device-resident, each step draws uniform with-replacement neighbors
(`replace=True` — the reference's own setting, train_dist.py:57) with
`jax.random`, and the only per-step host->device traffic is the
`[batch]` int32 seed ids.

Tree-form blocks, no frontier compaction
----------------------------------------
The host sampler (graph/blocks.py:build_fanout_blocks) compacts each
frontier to unique nodes, which needs data-dependent shapes — a host
operation by nature. Here every dst-node occurrence samples its own
fanout slots independently and nothing is deduplicated: layer sizes are
the closed-form ``n_{l+1} = n_l * (fanout_l + 1)`` (``tree_caps``),
fully static. For mean/sum aggregation the tree computation is
*distribution-identical* to the compacted one — compaction only caches
the aggregate of a repeated node, it does not change the sampled-
neighbor distribution — so training statistics match the host path and
the reference. The cost is duplicate feature gathers and aggregate
recomputation (~2x FLOPs at the bench shape), paid on a device whose
MXU is otherwise idle; the win is zero host sampling work, zero bulk
transfer, and sampling that scales with the chip, not the host core.

Block contract parity: blocks are emitted outermost-first with the
dst-prefix invariant (dst nodes are a prefix of each block's source
array), exactly like ``build_fanout_blocks`` — the FanoutSAGEConv /
FanoutGATConv stacks consume either sampler's output unchanged.

Scale note: single-chip device sampling needs indptr+indices in HBM
(int32: ~(N + E) * 4 bytes; ogbn-papers100M ~7 GB). Multi-host slices
keep per-partition CSRs on their own chips (the operator's partitioner
already shards the graph), so HBM holds 1/P of the edge list per chip.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dgl_operator_tpu.graph.blocks import FanoutBlock


def tree_caps(seed_cap: int, fanouts: Sequence[int]) -> List[int]:
    """Closed-form tree layer sizes, innermost (seeds) outward:
    ``n_{l+1} = n_l * (fanout_l + 1)`` with no graph-size clamp (the
    tree keeps duplicates, so it can exceed the node count)."""
    caps = [int(seed_cap)]
    for f in reversed(list(fanouts)):
        caps.append(caps[-1] * (int(f) + 1))
    return caps


def device_csr(csc: Tuple[np.ndarray, np.ndarray, np.ndarray]):
    """Stage a host CSC (indptr, indices, eids) onto the device for
    ``sample_fanout_tree``. int32 when the edge count allows (TPU-
    preferred width); eids are not needed for sampling and stay host."""
    indptr, indices, _ = csc
    # one width for both arrays: indptr holds offsets (bounded by the
    # edge count) but indices holds node IDS (bounded by the node
    # count) — either exceeding int32 forces the wide type
    n_nodes = len(indptr) - 1
    dt = (np.int32 if max(n_nodes, len(indices)) < 2**31 else np.int64)
    if len(indices) == 0:
        # clip-mode gather on a length-0 array is undefined; pad one
        # sentinel row (values are masked — every node has degree 0)
        # so an all-isolated-nodes graph still traces/executes cleanly,
        # matching the dummy-CSR trick DistTrainer's init uses
        indices = np.zeros(1, dtype=dt)
    return (jax.device_put(np.asarray(indptr, dtype=dt)),
            jax.device_put(np.asarray(indices, dtype=dt)))


def sample_fanout_tree(indptr, indices, seeds, fanouts: Sequence[int],
                       key) -> Tuple[List[FanoutBlock], jnp.ndarray]:
    """Multi-layer uniform with-replacement fanout sampling, traced.

    Parameters are device arrays / tracers; call this INSIDE jit (the
    trainer's step function). Returns ``(blocks, input_ids)`` with
    blocks outermost-first: drop-in for the host sampler's MiniBatch
    fields (``input_ids`` are global node ids for the feature gather).

    Negative seed ids (padding) sample garbage rows that are masked
    invalid, matching ``pad_minibatch`` semantics; zero-degree nodes
    likewise mask their whole fanout row.
    """
    f = jnp.maximum(seeds.astype(indptr.dtype), 0)
    valid = seeds >= 0
    per_layer = []
    for fan in reversed(list(fanouts)):
        key, sub = jax.random.split(key)
        n = f.shape[0]
        start = jnp.take(indptr, f, mode="clip")
        deg = jnp.take(indptr, f + 1, mode="clip") - start
        # uniform slot per (dst, fanout): draw wide, mod the degree —
        # modulo bias at degree ~1e9 vs 2^31 draws is negligible and
        # randint(minval per row) is not expressible per-element
        r = jax.random.randint(sub, (n, int(fan)), 0,
                               jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        r = r.astype(deg.dtype) % jnp.maximum(deg, 1)[:, None]
        nbr = jnp.take(indices, start[:, None] + r, mode="clip")
        mask = jnp.broadcast_to(((deg > 0) & valid)[:, None],
                                (n, int(fan)))
        # source array = [current frontier ++ sampled neighbors]: dst
        # node i sits at position i (prefix invariant), its sampled
        # slots at n + i*fan + j
        pos = (n + jnp.arange(n * int(fan), dtype=jnp.int32)
               .reshape(n, int(fan)))
        per_layer.append((pos, mask.astype(jnp.uint8), n * (int(fan) + 1)))
        f = jnp.concatenate(
            [f, jnp.where(mask, nbr, 0).reshape(-1)])
        valid = jnp.concatenate([valid, mask.reshape(-1)])
    blocks = [FanoutBlock(pos, m, ns)
              for pos, m, ns in reversed(per_layer)]
    return blocks, f
