"""Shape-aware Pallas-vs-XLA kernel dispatch for the aggregation hot
path.

``KERNELS_TPU.json`` (the r1–r3 artifact) records ONE whole-backend
recommendation, decided from two row widths — and its r3 incarnation
recorded raw multi-line compiler stderr as result values when the
Pallas toolchain 500'd, so the "kernel story" was neither per-shape
nor machine-readable. This module consumes the structured successor,
``benchmarks/KERNELS.json`` (written by ``benchmarks/bench_kernels.py``
with the record keys pinned in :mod:`dgl_operator_tpu.benchkeys`):
one entry per measured ``(rows, D, fanout)`` shape, each carrying an
``xla`` arm, a ``pallas`` arm (a timing, or a structured
``{status: "compile_error", detail}`` entry), and a per-shape
``recommendation``.

Dispatch (:func:`recommend`) picks the measured shape nearest the
queried one in log-space — kernel win/loss flips with arithmetic
intensity, which scales multiplicatively in rows/width/fanout, so
log-distance is the right metric — and returns its recommendation.
A shape whose Pallas arm failed to compile recommends ``xla`` by
construction: the failing kernel is *retired behind the dispatcher*
until a future benchmark run measures it healthy again (the
``ops.fanout`` consumers never guess). No table, or no usable entry →
``None`` and the caller falls back to the legacy whole-backend record.

Stdlib-only (+json): importable before jax is configured, like
``benchkeys``.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional

RECORD_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "KERNELS.json")

_cache: Dict[str, Optional[List[dict]]] = {}


def load_table(path: Optional[str] = None) -> Optional[List[dict]]:
    """The measured per-shape results, or ``None`` when the artifact
    is missing/unreadable (cached per path; :func:`reset_cache` for
    tests)."""
    path = path or RECORD_PATH
    if path in _cache:
        return _cache[path]
    table: Optional[List[dict]] = None
    try:
        with open(path) as f:
            record = json.load(f)
        rows = record.get("results")
        if isinstance(rows, list):
            table = [r for r in rows if isinstance(r, dict)
                     and r.get("recommendation") in ("pallas", "xla")]
    except (OSError, ValueError):
        table = None
    _cache[path] = table or None
    return _cache[path]


def reset_cache() -> None:
    _cache.clear()


def _log_distance(entry: dict, rows: int, d: int,
                  fanout: Optional[int]) -> float:
    """Log-space shape distance; a mismatched lane-alignment class
    (D % 128) is pushed far away — the Pallas kernels cannot run
    there at all, so a measured aligned shape must not vouch for an
    unaligned one."""
    def term(a, b):
        return abs(math.log(max(float(a), 1.0))
                   - math.log(max(float(b), 1.0)))

    dist = term(entry.get("rows", 1), rows) + term(entry.get("D", 1), d)
    if fanout is not None and entry.get("fanout") is not None:
        dist += term(entry["fanout"], fanout)
    if (int(entry.get("D", 0)) % 128 == 0) != (int(d) % 128 == 0):
        dist += 1e6
    return dist


def recommend(rows: int, d: int, fanout: Optional[int] = None,
              path: Optional[str] = None) -> Optional[str]:
    """``"pallas"`` / ``"xla"`` for the measured shape nearest
    ``(rows, d, fanout)``, or ``None`` when no per-shape table exists
    — the caller (``ops.fanout``) then falls back to the legacy
    whole-backend ``KERNELS_TPU.json`` recommendation."""
    table = load_table(path)
    if not table:
        return None
    best = min(table, key=lambda e: _log_distance(e, rows, d, fanout))
    return best["recommendation"]
