"""Model-health plane — in-program numerics sentry, divergence
detection, and the halt → rollback response (ISSUE 15).

The obs stack watches *systems* health exhaustively (job view, live
plane, SLO shedding, MFU/HBM profiling); nothing watched *model*
health: a NaN'd loss or an exploding gradient trains silently until
the epoch-end print — the reference DGL stack leaves this to the
user's own print statements, and at multi-slice scale it is the
failure mode that wastes the most accelerator-hours. This module
closes that gap in three layers:

- **in-program stats** (:func:`grad_stats` / :func:`dp_slot_stats`)
  — every training program (``parallel/dp.py make_dp_train_step``,
  SampledTrainer's step builders, DistKGETrainer's slot step) computes
  a small stats pytree *inside* the jitted step: global grad norm,
  param norm, update ratio, non-finite counts, and **per-partition
  loss / non-finite counts** so a fault localizes to a partition.
  TPU001-safe by construction: pure jnp math traced into the program,
  never host-side work in trace, and (on the non-WUS DP paths) ZERO
  additional collectives — per-partition members ride the dp out-spec
  and global scalars derive from values the update already reduced.
- **off-critical-path fetch** (:class:`StatsTap`) — the loop pushes
  each step's device handles and polls the *previous* step's at
  heartbeat cadence, so reading the stats never blocks on the step
  that was just dispatched (async dispatch stays async; the sentry
  trails reality by one step, which the quarantine bound accounts
  for).
- **rolling detectors + response** (:class:`QualityMonitor`) — a
  NaN/Inf sentry with first-bad-step + partition attribution, an EWMA
  loss-divergence z-score, a grad-norm explosion check against the
  rolling median, and a plateau detector. Detections emit
  ``train_quality_*`` gauges plus ``numerics_fault`` /
  ``loss_divergence`` / ``grad_explosion`` / ``loss_plateau`` events
  and Chrome counter tracks ("loss", "grad norm" — next to MFU in
  trace.json). A non-finite detection drives the automated response
  by ``quality_action``: ``warn`` keeps training (events only),
  ``halt`` raises :class:`NumericsFault` at the step boundary, and
  ``rollback`` additionally quarantines every checkpoint at or past
  the first bad step (``CheckpointManager.quarantine_from`` — the
  PR 13 fallback chain then restores the last-known-good) and leaves
  a workspace fault marker so ``tpurun`` relaunches the job with a
  bounded retry budget (``--numerics-retries``) instead of failing.

Chaos: the plan grammar gains ``numerics:nan:<step>``
(:class:`NumericsInjector`) — at that global step the trainer's
replicated params are poisoned with a NaN on the host, so the NEXT
step's gradients come out non-finite through the real backward pass;
the marker under ``<workspace>/.chaos_numerics_fired`` makes the
injection fire once across relaunches, which is what lets the
halt → rollback → resume path complete end to end
(``hack/quality_smoke.py``, ``make quality``).

Bit-exactness contract: a sentry-on trajectory is bit-identical to
sentry-off (the stats are pure read-only consumers of intermediates
the update already computes; pinned by tests/test_quality.py), and
the stats pytree adds no recompile (``jit_compiles_total`` unchanged).
Measured overhead is pinned in ``benchmarks/QUALITY.json``.
"""

from __future__ import annotations

import json
import math
import os
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

try:                         # the monitor/tap need numpy (trainer
    import numpy as np       # image); the analytics face
except ImportError:          # (model_health_summary, fault markers)
    np = None                # stays stdlib-only for the control plane

from dgl_operator_tpu.obs import get_obs

# the workspace fault marker the halting trainer writes and the tpurun
# rollback loop consumes (same cross-process contract as the chaos
# dead-host markers: a shared filesystem, launcher/chaos.WORKSPACE_ENV)
FAULT_MARKER = ".numerics_fault.json"
# the chaos numerics:nan fired-once marker (a rollback resumes BELOW
# the injection step, so a per-process latch alone would re-poison the
# recovered run forever)
NUMERICS_FIRED_MARKER = ".chaos_numerics_fired"
# retryable exit status for entry scripts that catch NumericsFault:
# distinct from 75/EX_TEMPFAIL (Preempted) so operators can tell a
# rollback relaunch from a preemption requeue in the exit-code ledger
NUMERICS_FAULT_EXIT = 76

_EPS = 1e-12


class NumericsFault(RuntimeError):
    """The numerics sentry detected non-finite training state and the
    configured ``quality_action`` is ``halt`` or ``rollback``: the
    trainer stops cleanly at the step boundary. ``step`` is the first
    bad global step, ``partition`` the attributed partition (None when
    attribution found nothing sharper than "everywhere")."""

    def __init__(self, msg: str, step: int,
                 partition: Optional[int] = None,
                 kind: str = "nonfinite"):
        super().__init__(msg)
        self.step = int(step)
        self.partition = partition
        self.kind = kind


# ---------------------------------------------------------------------
# in-program stats (pure jnp — traced into the training programs)
# ---------------------------------------------------------------------
def _sq_sum(tree):
    import jax
    import jax.numpy as jnp
    total = jnp.float32(0.0)
    for leaf in jax.tree.leaves(tree):
        total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def _nonfinite_count(tree):
    import jax
    import jax.numpy as jnp
    total = jnp.int32(0)
    for leaf in jax.tree.leaves(tree):
        total = total + jnp.sum(
            (~jnp.isfinite(leaf.astype(jnp.float32))).astype(jnp.int32))
    return total


def grad_stats(loss, grads, updates, params) -> Dict:
    """The single-replica stats pytree (SampledTrainer's step
    builders): global grad/param norms, the update ratio, and the
    non-finite element count over the raw gradients + the loss. Pure
    jnp — call it inside the jitted step."""
    import jax.numpy as jnp
    gsq = _sq_sum(grads)
    psq = _sq_sum(params)
    usq = _sq_sum(updates)
    nonfin = _nonfinite_count(grads) + (
        ~jnp.isfinite(loss)).astype(jnp.int32)
    pn = jnp.sqrt(psq)
    return {"grad_norm": jnp.sqrt(gsq), "param_norm": pn,
            "update_ratio": jnp.sqrt(usq) / (pn + _EPS),
            "nonfinite": nonfin}


def dp_slot_stats(loss_local, grads_raw, grads_reduced, updates,
                  params) -> Dict:
    """The per-mesh-slot stats pytree of the DP train step
    (``parallel/dp.py``), computed inside shard_map with ZERO extra
    collectives: ``part_loss`` / ``part_nonfinite`` are this slot's
    own values (dp out-spec stacks them into ``[P]`` — the partition
    attribution), while grad/param/update norms and the global
    non-finite count derive from the already-pmean'd gradients and
    the replicated updated params, so they are replicated without any
    new reduction (a NaN in any slot's raw grads propagates through
    the pmean into every slot's reduced view)."""
    import jax.numpy as jnp
    gsq = _sq_sum(grads_reduced)
    psq = _sq_sum(params)
    usq = _sq_sum(updates)
    pn = jnp.sqrt(psq)
    nonfin_local = _nonfinite_count(grads_raw) + (
        ~jnp.isfinite(loss_local)).astype(jnp.int32)
    return {"grad_norm": jnp.sqrt(gsq), "param_norm": pn,
            "update_ratio": jnp.sqrt(usq) / (pn + _EPS),
            "nonfinite": _nonfinite_count(grads_reduced),
            "part_loss": loss_local.astype(jnp.float32)[None],
            "part_nonfinite": nonfin_local[None]}


def zero_stats_like(per_part: bool = True) -> Dict:
    """A zeros-valued stats pytree with the exact structure/dtypes of
    :func:`dp_slot_stats` (or :func:`grad_stats` when
    ``per_part=False``) — the ``lax.scan`` carry initializer of the
    multi-step programs."""
    import jax.numpy as jnp
    out = {"grad_norm": jnp.float32(0.0), "param_norm": jnp.float32(0.0),
           "update_ratio": jnp.float32(0.0), "nonfinite": jnp.int32(0)}
    if per_part:
        out["part_loss"] = jnp.zeros((1,), jnp.float32)
        out["part_nonfinite"] = jnp.zeros((1,), jnp.int32)
    return out


# ---------------------------------------------------------------------
# off-critical-path fetch
# ---------------------------------------------------------------------
def _host_leaf(x) -> np.ndarray:
    """One stats leaf to host, multi-controller-safe: a jax.Array with
    non-addressable shards (dp-sharded ``part_*`` members, replicated
    scalars) materializes from its LOCAL shards only — a replicated
    leaf reads any one shard, a dp-sharded leaf concatenates this
    process's rows (which align with its ``my_parts`` block)."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        shards = sorted(x.addressable_shards,
                        key=lambda s: tuple(
                            (sl.start or 0) for sl in s.index))
        if shards and tuple(shards[0].data.shape) == tuple(x.shape):
            return np.asarray(shards[0].data)
        seen, parts = set(), []
        for s in shards:
            key = tuple((sl.start or 0) for sl in s.index)
            if key in seen:
                continue
            seen.add(key)
            parts.append(np.asarray(s.data))
        return np.concatenate(parts) if parts else np.zeros(0)
    return np.asarray(x)


class StatsTap:
    """One-step-delayed host fetch of the in-program stats: the loop
    pushes each dispatch's (step, loss, stats) device handles, and
    :meth:`poll` materializes only entries older than ``delay``
    dispatches — blocking (at worst) on a step the device has already
    retired behind the one in flight, never on the step just
    dispatched. The sentry therefore trails training by ``delay``
    steps, which is why the rollback quarantine starts at the first
    *observed* bad step, not the last checkpoint."""

    def __init__(self, delay: int = 1, max_lag: int = 8):
        self.delay = max(int(delay), 0)
        # bounded staleness: past this many un-fetched dispatches the
        # oldest is fetched even if it means waiting on the device
        self.max_lag = max(int(max_lag), self.delay + 1)
        self._pending: deque = deque()

    def push(self, step: int, loss, stats: Optional[Dict]) -> None:
        self._pending.append((int(step), loss, stats))

    def poll(self) -> Optional[Tuple[int, float, Optional[Dict]]]:
        """The newest ripe entry (older than ``delay`` dispatches AND
        already materialized on device — ``jax.Array.is_ready`` —
        so the loop thread never waits on an in-flight step), fetched
        to host; None when nothing is ripe yet. The ``max_lag`` bound
        forces a fetch when the backlog grows, so the sentry can trail
        training by at most that many steps."""
        out = None
        while len(self._pending) > self.delay:
            step, loss, stats = self._pending[0]
            ready = getattr(loss, "is_ready", None)
            if (ready is not None and len(self._pending) <= self.max_lag
                    and not ready()):
                break
            self._pending.popleft()
            host = (None if stats is None else
                    {k: _host_leaf(v) for k, v in stats.items()})
            out = (step, float(_host_leaf(loss)), host)
        return out

    def drain(self) -> Optional[Tuple[int, float, Optional[Dict]]]:
        """Fetch everything (epoch end / teardown): the final steps
        must not escape the sentry just because the loop ended."""
        out = None
        while self._pending:
            step, loss, stats = self._pending.popleft()
            host = (None if stats is None else
                    {k: _host_leaf(v) for k, v in stats.items()})
            out = (step, float(_host_leaf(loss)), host)
        return out


# ---------------------------------------------------------------------
# rolling detectors
# ---------------------------------------------------------------------
class QualityMonitor:
    """Host-side rolling detectors over the stats stream. One
    instance per trainer process; :meth:`observe` is called at
    heartbeat cadence with the tap's fetched (step, loss, stats).

    Detectors:

    - **NaN/Inf sentry** — any non-finite loss or gradient element:
      ``numerics_fault`` event with the first bad step and the
      attributed partition (argmax of ``part_nonfinite``, falling
      back to the partition whose ``part_loss`` is non-finite);
      raises :class:`NumericsFault` unless ``action="warn"``.
    - **loss divergence** — EWMA z-score of the loss against its own
      rolling mean/variance (``quality_z_max``): ``loss_divergence``
      event on the rising edge.
    - **grad explosion** — grad norm above ``quality_grad_ratio_max``
      × the rolling median grad norm: ``grad_explosion`` event on the
      rising edge.
    - **plateau** — loss range over ``quality_plateau_window`` steps
      below ``quality_plateau_rel`` of its magnitude: ``loss_plateau``
      info event (0 disables).

    Every observation lands in the ``train_quality_*`` gauges and the
    "loss"/"grad norm" Chrome counter tracks, so Perfetto shows model
    health next to MFU.
    """

    def __init__(self, window: int = 32, z_max: float = 6.0,
                 grad_ratio_max: float = 50.0,
                 plateau_window: int = 0, plateau_rel: float = 1e-3,
                 action: str = "rollback",
                 parts: Optional[Sequence[int]] = None,
                 min_samples: int = 8):
        from dgl_operator_tpu.autotune.knobs import validate
        self.window = validate("quality_window", int(window))
        self.z_max = validate("quality_z_max", float(z_max))
        self.grad_ratio_max = validate("quality_grad_ratio_max",
                                       float(grad_ratio_max))
        self.plateau_window = validate("quality_plateau_window",
                                       int(plateau_window))
        self.plateau_rel = validate("quality_plateau_rel",
                                    float(plateau_rel))
        self.action = validate("quality_action", action)
        self.parts = list(parts) if parts is not None else None
        self.min_samples = int(min_samples)
        self._alpha = 2.0 / (self.window + 1.0)
        self._ewma_mean: Optional[float] = None
        self._ewma_var: float = 0.0
        self._n = 0
        self._loss_hist: deque = deque(maxlen=max(
            self.window, self.plateau_window or 1))
        self._grad_hist: deque = deque(maxlen=self.window)
        self._diverging = False
        self._exploding = False
        self._plateaued = False
        self.fault: Optional[NumericsFault] = None
        self.last: Dict = {}

    @classmethod
    def from_config(cls, cfg, parts: Optional[Sequence[int]] = None
                    ) -> "QualityMonitor":
        """Build from a trainer config carrying the quality knob
        fields (TrainConfig / KGETrainConfig)."""
        return cls(window=getattr(cfg, "quality_window", 32),
                   z_max=getattr(cfg, "quality_z_max", 6.0),
                   grad_ratio_max=getattr(cfg, "quality_grad_ratio_max",
                                          50.0),
                   plateau_window=getattr(cfg, "quality_plateau_window",
                                          0),
                   plateau_rel=getattr(cfg, "quality_plateau_rel",
                                       1e-3),
                   action=getattr(cfg, "quality_action", "rollback"),
                   parts=parts)

    # -- attribution ---------------------------------------------------
    def _attribute(self, stats: Optional[Dict]) -> Optional[int]:
        if stats:
            arr = stats.get("part_nonfinite")
            if arr is not None:
                arr = np.asarray(arr).reshape(-1)
                if len(arr) and arr.max() > 0:
                    i = int(arr.argmax())
                    return (self.parts[i] if self.parts is not None
                            and i < len(self.parts) else i)
            pl = stats.get("part_loss")
            if pl is not None:
                pl = np.asarray(pl).reshape(-1)
                bad = np.nonzero(~np.isfinite(pl))[0]
                if len(bad):
                    i = int(bad[0])
                    return (self.parts[i] if self.parts is not None
                            and i < len(self.parts) else i)
        if self.parts is not None and len(self.parts) == 1:
            # single-partition trainer (SampledTrainer under the
            # launcher's per-rank contract): the fault IS this part
            return self.parts[0]
        return None

    # -- the one entry point ------------------------------------------
    def observe(self, step: int, loss: float,
                stats: Optional[Dict] = None) -> Dict:
        """One fetched observation. Returns the verdict dict (also
        kept as ``self.last``); raises :class:`NumericsFault` when the
        sentry trips and the action is halt/rollback."""
        obs = get_obs()
        m = obs.metrics
        gnorm = pnorm = uratio = None
        nonfin = 0
        if stats:
            if stats.get("grad_norm") is not None:
                gnorm = float(np.asarray(stats["grad_norm"]))
            if stats.get("param_norm") is not None:
                pnorm = float(np.asarray(stats["param_norm"]))
            if stats.get("update_ratio") is not None:
                uratio = float(np.asarray(stats["update_ratio"]))
            if stats.get("nonfinite") is not None:
                nonfin = int(np.asarray(stats["nonfinite"]).sum())
            elif stats.get("part_nonfinite") is not None:
                nonfin = int(np.asarray(
                    stats["part_nonfinite"]).sum())
        bad = nonfin > 0 or not math.isfinite(loss)
        if gnorm is not None and not math.isfinite(gnorm):
            bad = True
        # gauges first — the stream must be visible even on the step
        # that trips the sentry
        if gnorm is not None and math.isfinite(gnorm):
            m.gauge("train_quality_grad_norm",
                    "global L2 gradient norm at the last observed "
                    "step").set(round(gnorm, 6))
        if pnorm is not None and math.isfinite(pnorm):
            m.gauge("train_quality_param_norm",
                    "global L2 parameter norm at the last observed "
                    "step").set(round(pnorm, 6))
        if uratio is not None and math.isfinite(uratio):
            m.gauge("train_quality_update_ratio",
                    "L2(update)/L2(params) of the last observed "
                    "step").set(round(uratio, 8))
        if nonfin:
            m.counter("train_quality_nonfinite_total",
                      "non-finite gradient/loss elements observed by "
                      "the numerics sentry").inc(nonfin)
        track = {}
        if math.isfinite(loss):
            track["loss"] = round(loss, 6)
        if gnorm is not None and math.isfinite(gnorm):
            track["grad_norm"] = round(gnorm, 6)
        if track:
            obs.tracer.counter("model health", track)
        verdict: Dict = {"step": int(step), "loss": loss,
                         "grad_norm": gnorm, "param_norm": pnorm,
                         "update_ratio": uratio, "nonfinite": nonfin,
                         "ok": not bad}
        if bad:
            part = self._attribute(stats)
            verdict["partition"] = part
            self.last = verdict
            self._fault(step, loss, part, nonfin)
            return verdict            # action == "warn" falls through
        self._divergence(step, loss)
        self._explosion(step, gnorm)
        self._plateau(step, loss)
        verdict["loss_z"] = self._z(loss)
        self.last = verdict
        self._loss_hist.append(loss)
        if gnorm is not None:
            self._grad_hist.append(gnorm)
        self._update_ewma(loss)
        return verdict

    # -- NaN/Inf -------------------------------------------------------
    def _fault(self, step: int, loss: float, part: Optional[int],
               nonfin: int) -> None:
        obs = get_obs()
        kind = "nonfinite_loss" if not math.isfinite(loss) \
            else "nonfinite_grad"
        obs.metrics.counter(
            "train_quality_faults_total",
            "numerics-sentry detections (non-finite loss/grads)",
            labels=("kind",)).inc(kind=kind)
        obs.events.emit("numerics_fault", step=int(step),
                        partition=part, kind=kind,
                        nonfinite=int(nonfin), action=self.action,
                        loss=(loss if math.isfinite(loss) else None))
        obs.tracer.instant("numerics_fault", cat="quality",
                           step=int(step))
        obs.flush()
        fault = NumericsFault(
            f"numerics sentry: {kind} at step {step}"
            + (f" on partition {part}" if part is not None else "")
            + f" ({nonfin} non-finite element(s); action="
            f"{self.action})", step, partition=part, kind=kind)
        self.fault = fault
        if self.action != "warn":
            raise fault

    # -- divergence ----------------------------------------------------
    def _z(self, loss: float) -> Optional[float]:
        if self._ewma_mean is None or self._n < self.min_samples:
            return None
        std = math.sqrt(max(self._ewma_var, 0.0))
        return (loss - self._ewma_mean) / max(std, _EPS)

    def _update_ewma(self, loss: float) -> None:
        if self._ewma_mean is None:
            self._ewma_mean = loss
            self._ewma_var = 0.0
        else:
            d = loss - self._ewma_mean
            self._ewma_mean += self._alpha * d
            self._ewma_var = ((1.0 - self._alpha)
                              * (self._ewma_var + self._alpha * d * d))
        self._n += 1

    def _divergence(self, step: int, loss: float) -> None:
        z = self._z(loss)
        if z is None:
            return
        get_obs().metrics.gauge(
            "train_quality_loss_z",
            "EWMA z-score of the last observed loss").set(round(z, 4))
        if z > self.z_max and not self._diverging:
            self._diverging = True
            obs = get_obs()
            obs.metrics.counter(
                "train_quality_divergences_total",
                "loss-divergence detections (EWMA z-score over "
                "quality_z_max)").inc()
            obs.events.emit("loss_divergence", step=int(step),
                            loss=round(loss, 6), z=round(z, 4),
                            z_max=self.z_max,
                            mean=round(self._ewma_mean, 6))
        elif z <= self.z_max:
            self._diverging = False

    # -- explosion -----------------------------------------------------
    def _explosion(self, step: int, gnorm: Optional[float]) -> None:
        if gnorm is None or self.grad_ratio_max <= 0:
            return
        if len(self._grad_hist) < self.min_samples:
            return
        med = float(np.median(np.asarray(self._grad_hist)))
        if med <= 0:
            return
        if gnorm > self.grad_ratio_max * med and not self._exploding:
            self._exploding = True
            obs = get_obs()
            obs.metrics.counter(
                "train_quality_grad_explosions_total",
                "grad-norm explosion detections (norm over "
                "quality_grad_ratio_max x rolling median)").inc()
            obs.events.emit("grad_explosion", step=int(step),
                            grad_norm=round(gnorm, 6),
                            median=round(med, 6),
                            ratio=round(gnorm / med, 3),
                            ratio_max=self.grad_ratio_max)
        elif gnorm <= self.grad_ratio_max * med:
            self._exploding = False

    # -- plateau -------------------------------------------------------
    def _plateau(self, step: int, loss: float) -> None:
        w = self.plateau_window
        if not w or len(self._loss_hist) < w:
            return
        recent = list(self._loss_hist)[-w:] + [loss]
        spread = max(recent) - min(recent)
        scale = max(abs(sum(recent) / len(recent)), _EPS)
        if spread <= self.plateau_rel * scale and not self._plateaued:
            self._plateaued = True
            get_obs().events.emit("loss_plateau", step=int(step),
                                  loss=round(loss, 6),
                                  window=w,
                                  spread=round(spread, 8))
        elif spread > self.plateau_rel * scale:
            self._plateaued = False


# ---------------------------------------------------------------------
# the automated response (trainer side)
# ---------------------------------------------------------------------
def _workspace() -> Optional[str]:
    from dgl_operator_tpu.launcher.chaos import WORKSPACE_ENV
    return os.environ.get(WORKSPACE_ENV)


def write_fault_marker(fault: NumericsFault,
                       workspace: Optional[str] = None) -> Optional[str]:
    """Record the fault under ``<workspace>/.numerics_fault.json`` —
    the signal the ``tpurun`` rollback loop (bounded by
    ``--numerics-retries``) relaunches on. Best-effort: no workspace
    (unit tests, standalone trainers) costs the run the automatic
    relaunch, never the clean halt."""
    ws = workspace or _workspace()
    if not ws:
        return None
    path = os.path.join(ws, FAULT_MARKER)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": fault.step, "partition": fault.partition,
                       "kind": fault.kind, "pid": os.getpid()}, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def take_fault_marker(workspace: str) -> Optional[Dict]:
    """Consume (read + delete) the workspace fault marker — the driver
    side of the rollback handshake. None when no trainer faulted."""
    path = os.path.join(workspace, FAULT_MARKER)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    try:
        os.remove(path)
    except OSError:
        pass
    return rec if isinstance(rec, dict) else None


def my_partition() -> int:
    """The partition id this single-partition trainer process runs as
    (the launcher's per-rank env; the elastic hostfile contract makes
    rank == partition). 0 when standalone."""
    from dgl_operator_tpu.parallel.bootstrap import RANK_ENV
    try:
        return int(os.environ.get(RANK_ENV, "0") or 0)
    except ValueError:
        return 0


def halt_for_rollback(fault: NumericsFault, ckpt=None,
                      action: str = "rollback") -> None:
    """The shared trainer epilogue for a tripped sentry: with
    ``action="rollback"`` quarantine every checkpoint at or past the
    first bad step (restore's candidate scan then lands on the
    last-known-good) and leave the workspace fault marker for the
    driver's bounded relaunch; ``action="halt"`` skips both — the
    operator decides what happens next. Either way the halt is
    evented, telemetry flushed, and the fault re-raised so the loop
    stops cleanly at the step boundary."""
    obs = get_obs()
    rolled = None
    marker = None
    if action == "rollback":
        if ckpt is not None:
            try:
                rolled = ckpt.quarantine_from(fault.step)
            except Exception as exc:  # noqa: BLE001 — must not mask
                obs.events.log(
                    f"checkpoint quarantine failed ({exc}); restore "
                    "may land on a post-fault checkpoint",
                    event="ckpt_quarantine_failed",
                    error=str(exc)[:300])
        marker = write_fault_marker(fault)
    obs.events.emit("numerics_halt", step=fault.step,
                    partition=fault.partition, kind=fault.kind,
                    action=action, rolled_back_to=rolled,
                    marker=bool(marker))
    obs.flush()
    raise fault


# ---------------------------------------------------------------------
# chaos: numerics:nan:<step>
# ---------------------------------------------------------------------
class NumericsInjector:
    """The chaos ``numerics:nan:<step>`` edge: at the first loop
    boundary at or past ``<step>`` the trainer's replicated params are
    poisoned with a NaN (one leaf, scaled by ``nan`` on host — the
    next step's backward pass then produces genuinely non-finite
    gradients through the real program). Fires ONCE per workspace
    (``.chaos_numerics_fired`` marker), because a rollback resumes
    *below* the injection step and a re-firing rule would trap the
    job in a poison → rollback loop forever. The same start-step
    guard as ``train:kill`` keeps runs that start at or past the step
    (the recovered relaunch on a markerless workspace) alive."""

    def __init__(self, start_step: int = 0):
        from dgl_operator_tpu.launcher.chaos import proc_plan
        plan = proc_plan()
        at = plan.numerics_nan_step() if plan else None
        self.at = (at if at is not None and at > start_step else None)
        if self.at is not None and self._fired_marker_exists():
            self.at = None

    @staticmethod
    def _fired_path() -> Optional[str]:
        ws = _workspace()
        return os.path.join(ws, NUMERICS_FIRED_MARKER) if ws else None

    def _fired_marker_exists(self) -> bool:
        p = self._fired_path()
        return bool(p) and os.path.exists(p)

    def _mark_fired(self) -> None:
        p = self._fired_path()
        if not p:
            return
        try:
            with open(p, "w") as f:
                f.write(f"pid={os.getpid()}\n")
        except OSError:
            pass

    def maybe_poison(self, gstep: int, params):
        """Call once per loop iteration AFTER the checkpoint/heartbeat
        epilogue (so the last pre-poison checkpoint stays clean —
        that IS the last-known-good the rollback restores). Returns
        the (possibly poisoned) params."""
        if self.at is None or gstep < self.at:
            return params
        self.at = None
        self._mark_fired()
        import jax
        import jax.numpy as jnp
        obs = get_obs()
        obs.metrics.counter(
            "chaos_faults_injected_total",
            "faults the chaos plan actually delivered",
            labels=("verb", "action")).inc(verb="numerics",
                                           action="nan")
        obs.events.emit("chaos_numerics_nan", step=int(gstep))
        obs.tracer.instant("chaos_numerics_nan", cat="chaos",
                           step=int(gstep))
        leaves, treedef = jax.tree.flatten(params)
        leaves = [leaves[0] * jnp.float32(float("nan"))] + leaves[1:]
        return jax.tree.unflatten(treedef, leaves)


def maybe_injector(start_step: int = 0) -> Optional[NumericsInjector]:
    """An armed injector, or None when the chaos plan carries no
    ``numerics:nan`` rule (the common case — zero per-step work)."""
    inj = NumericsInjector(start_step)
    return inj if inj.at is not None else None


# ---------------------------------------------------------------------
# analytics face (stdlib-only — doctor/analyze import through here)
# ---------------------------------------------------------------------
def model_health_summary(events: List[Dict],
                         procs: Dict[str, dict]) -> Optional[Dict]:
    """The model-health roll-up of a job view: numerics faults (with
    step/partition attribution), divergence/explosion/plateau counts,
    rollbacks, and the last observed quality gauges. None when the
    run never carried the sentry (pre-quality runs are unchanged)."""
    faults = [e for e in events if e.get("event") == "numerics_fault"]
    div = [e for e in events if e.get("event") == "loss_divergence"]
    exp = [e for e in events if e.get("event") == "grad_explosion"]
    plat = [e for e in events if e.get("event") == "loss_plateau"]
    rb = [e for e in events if e.get("event") == "numerics_rollback"]

    def gauge(name: str) -> Optional[float]:
        best = None
        for snap in (procs or {}).values():
            for s in ((snap or {}).get(name) or {}).get("samples", []):
                v = float(s["value"])
                best = v if best is None else max(best, v)
        return best

    gnorm = gauge("train_quality_grad_norm")
    uratio = gauge("train_quality_update_ratio")
    loss = gauge("train_loss")
    if not (faults or div or exp or plat or rb) and gnorm is None:
        return None
    return {
        "faults": [{"step": e.get("step"),
                    "partition": e.get("partition"),
                    "kind": e.get("kind"),
                    "action": e.get("action")} for e in faults],
        "divergences": len(div),
        "grad_explosions": len(exp),
        "plateaus": len(plat),
        "rollbacks": len(rb),
        "last_loss": loss,
        "last_grad_norm": gnorm,
        "last_update_ratio": uratio,
    }
