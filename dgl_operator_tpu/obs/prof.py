"""``tpu-prof`` — hardware-utilization introspection: per-step
MFU/roofline accounting, XLA compile/recompile telemetry, HBM
watermark sampling, and a perf-regression gate.

The obs plane (PRs 4, 5, 11) can say a job is *slow or stuck*; nothing
can say *how far from the hardware ceiling* it runs. This module closes
that gap the way production training stacks do (GSPMD-style systems
report model-FLOPs utilization against a roofline, PAPERS.md):

- **cost accounting** — per-step analytic FLOPs and bytes from the
  jitted step via ``lower().cost_analysis()`` (no extra XLA compile:
  the unoptimized-HLO analysis is enough for a roofline), with a
  coarse per-model analytic fallback (:func:`analytic_train_cost`)
  when the backend reports nothing. Combined with measured step time
  and a per-platform peak table (the ``prof`` knob layer:
  ``peak_flops`` / ``peak_hbm_gbps``, CPU defaults auto-detected),
  every heartbeat window emits ``train_mfu`` and
  ``train_roofline_frac{bound=compute|memory|comm}`` gauges plus
  Chrome counter tracks (``MFU``, ``HBM MiB``) so Perfetto shows
  utilization under the span tree.
- **compile telemetry** — :func:`instrument_jit` wraps a jitted
  callable and detects every XLA compile from executable-cache growth:
  ``jit_compiles_total{fn}``, ``jit_compile_seconds``, and a
  ``jit_compile`` event whose ``steady`` flag marks compiles that
  happened after the function's warmup calls — shape churn after
  warmup is the silent 10x killer the ``runtime/loop.py`` padding
  invariant exists to prevent, and ``obs/analyze.py`` turns those
  events into a critical finding.
- **memory watermarks** — per-device live-buffer high-water sampling
  (``device.memory_stats()`` where the backend has it, live-array
  shard accounting otherwise) folded into the heartbeat as
  ``train_hbm_watermark_mib{device}``, reconciled by the analytics
  against the trainer's analytic ``train_hbm_predicted_mib`` model
  (drift > 20% is a finding).
- **regression gate** — :func:`prof_summary` extracts the pinned prof
  keys (``benchkeys.PROF_KEYS``) from a run's obs view and
  :func:`diff_summaries` compares two of them under an adoption
  margin; ``tpu-prof diff <run> <baseline>`` is the CLI face and
  ``make prof-gate`` fails CI when MFU or the step rate regresses.

Import-light on purpose: jax is imported lazily inside the functions
that need it, so the CLI (``tpu-prof``) and the analytics run in the
control-plane image.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dgl_operator_tpu.benchkeys import PROF_KEYS

PEAK_FLOPS_ENV = "TPU_OPERATOR_PEAK_FLOPS"
PEAK_HBM_ENV = "TPU_OPERATOR_PEAK_HBM_GBPS"

# calls a training program may legitimately compile on before the
# compile counts as steady-state (call 0 always compiles; call 1 covers
# a second legitimate shape such as a donation-rebound warm call)
DEFAULT_WARMUP_CALLS = 2
# measured-vs-predicted HBM drift tolerance (the analytics finding)
DEFAULT_HBM_DRIFT_FRAC = 0.20
# default adoption margin of the regression gate: a run must not fall
# more than this fraction below the baseline on a gated key
DEFAULT_DIFF_MARGIN = 0.15

# peak table by accelerator generation (dense per-chip peaks; bf16
# FLOPs, HBM GB/s). Indicative numbers for the roofline DENOMINATOR —
# calibrate with the prof knobs for headline claims
# (docs/profiling.md).
_DEVICE_PEAKS = (
    ("v5e", 197e12, 819.0),
    ("v5p", 459e12, 2765.0),
    ("v4", 275e12, 1228.0),
    ("v3", 123e12, 900.0),
    ("v2", 45e12, 700.0),
)
# CPU fallback: per-core peak (8-wide FMA at ~2 GHz) and a socket-ish
# memory bandwidth. Deliberately round numbers: the CPU roofline is a
# smoke/test surface, not a headline
_CPU_FLOPS_PER_CORE = 32e9
_CPU_HBM_GBPS = 25.0


@dataclasses.dataclass
class ProfConfig:
    """The prof knob layer (autotune registry ``layer="prof"``):
    roofline peaks in FLOP/s and GB/s. ``0`` = auto-detect from the
    backend (:func:`resolve_peaks`). Tunable through the same
    ``tuned.json`` / env path as every other knob."""

    peak_flops: float = 0.0
    peak_hbm_gbps: float = 0.0


def resolve_peaks(cfg: Optional[ProfConfig] = None) -> Dict:
    """The roofline denominators, resolved in priority order: explicit
    config > ``TPU_OPERATOR_PEAK_*`` env > tuned manifest (via
    ``apply_tuned`` on the default config) > platform auto-detection.
    All values ride the knob registry's validation (TPU004: no inline
    range checks)."""
    from dgl_operator_tpu.autotune.knobs import apply_tuned, validate
    cfg = apply_tuned(cfg or ProfConfig(), layer="prof")
    flops = validate("peak_flops", cfg.peak_flops)
    gbps = validate("peak_hbm_gbps", cfg.peak_hbm_gbps)
    if flops and gbps:
        return {"peak_flops": flops, "peak_hbm_gbps": gbps,
                "source": "config"}
    env_f = os.environ.get(PEAK_FLOPS_ENV)
    env_b = os.environ.get(PEAK_HBM_ENV)
    if env_f:
        flops = flops or validate("peak_flops", float(env_f))
    if env_b:
        gbps = gbps or validate("peak_hbm_gbps", float(env_b))
    if flops and gbps:
        return {"peak_flops": flops, "peak_hbm_gbps": gbps,
                "source": "env"}
    auto = _detect_peaks()
    return {"peak_flops": flops or auto[0],
            "peak_hbm_gbps": gbps or auto[1],
            "source": auto[2]}


def _detect_peaks() -> Tuple[float, float, str]:
    """Platform auto-detection: a per-generation table for TPUs, a
    core-count model for CPU (the virtual-mesh devices time-share one
    host, so the CPU peak is the HOST peak, not cores x devices)."""
    try:
        import jax
        dev = jax.devices()[0]
        platform = getattr(dev, "platform", "cpu")
        kind = str(getattr(dev, "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 — no backend: CPU model
        platform, kind = "cpu", ""
    if platform == "tpu":
        for tag, flops, gbps in _DEVICE_PEAKS:
            if tag in kind:
                return flops, gbps, f"auto:{tag}"
        return _DEVICE_PEAKS[0][1], _DEVICE_PEAKS[0][2], "auto:tpu"
    cores = os.cpu_count() or 1
    return cores * _CPU_FLOPS_PER_CORE, _CPU_HBM_GBPS, "auto:cpu"


# ------------------------------------------------------- cost models
def cost_from_lowered(lowered) -> Optional[Tuple[float, float]]:
    """(flops, bytes accessed) out of a ``Lowered.cost_analysis()``
    result — dict on newer jax, a one-element list of dicts on older;
    ``None`` when the backend reports nothing usable (XLA:CPU on some
    program shapes), which routes the caller to the analytic
    fallback."""
    try:
        ca = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — backend without the analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops") or 0.0)
    nbytes = float(ca.get("bytes accessed") or 0.0)
    if flops <= 0.0:
        return None
    return flops, nbytes


def jit_step_cost(jitted, *args, **kwargs) -> Optional[Dict]:
    """Per-call FLOPs/bytes of a jitted program from its lowering
    (traces once, compiles nothing). ``None`` when the program cannot
    be lowered here or the backend reports no cost — callers fall back
    to :func:`analytic_train_cost`."""
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception:  # noqa: BLE001 — fallback path, never fatal
        return None
    cost = cost_from_lowered(lowered)
    if cost is None:
        return None
    return {"flops": cost[0], "bytes": cost[1],
            "source": "xla_cost_analysis"}


def analytic_train_cost(param_count: int, input_rows: int,
                        feat_dim: int, edge_count: int) -> Dict:
    """Coarse per-optimizer-step cost model for a sampled GNN train
    step, used when XLA reports no cost: dense work ~ every parameter
    applied per input row, message work ~ one multiply-add per edge
    feature element, and fwd+bwd+update ~ 3x the forward (the standard
    2x-backward + update bound). Bytes ~ one read+write of the
    activations plus two passes over the parameters (grads + update).
    Deliberately conservative and documented (docs/profiling.md):
    the fallback exists so MFU is *comparable across runs*, not
    absolutely calibrated."""
    fwd = 2.0 * float(param_count) * max(input_rows, 1) \
        + 2.0 * float(edge_count) * max(feat_dim, 1)
    act_bytes = 4.0 * max(input_rows, 1) * max(feat_dim, 1)
    nbytes = 3.0 * (2.0 * act_bytes + 2.0 * 4.0 * float(param_count))
    return {"flops": 3.0 * fwd, "bytes": nbytes, "source": "analytic"}


def gather_staging_mib(leaf_bytes, gather_depth: int) -> float:
    """ZeRO-3 transient-HBM term for the analytic per-slot bill
    (``train_hbm_predicted_mib``): under ``zero_stage=3`` the step's
    fused all-gather window keeps up to ``gather_depth`` FULL
    (materialized) parameter leaves in flight on top of the persistent
    1/N shards. The bound bills the ``gather_depth`` LARGEST leaves —
    the worst window the depth-bounded pipeline can hold — so the
    measured watermark reconciles against the prediction instead of
    tripping the hbm_drift finding. ``leaf_bytes`` is the per-leaf
    FULL (gathered) byte sizes; returns MiB."""
    depth = max(int(gather_depth), 1)
    top = sorted((float(b) for b in leaf_bytes), reverse=True)[:depth]
    return sum(top) / 2.0**20


# --------------------------------------------- compile instrumentation
class _InstrumentedJit:
    """Wrapper around a jitted callable: counts calls, detects XLA
    compiles from executable-cache growth (``_cache_size``), records
    compile time + the ``steady`` flag, and (for training-role
    programs) contributes its per-call cost to the process profiler.
    Everything else — ``lower``, ``init_opt_state``, the HLO-inspection
    seams — passes through to the wrapped function."""

    def __init__(self, name: str, fn, role: Optional[str] = None,
                 warmup_calls: Optional[int] = DEFAULT_WARMUP_CALLS):
        object.__setattr__(self, "_inner", fn)
        self.name = name
        self.role = role
        self.warmup_calls = warmup_calls
        self.calls = 0
        self.compiles = 0
        self._cost_done = False

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_inner"), item)

    def _cache_size(self) -> Optional[int]:
        fn = object.__getattribute__(self, "_inner")
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 — telemetry never raises
            return None

    def _note_cost(self, args, kwargs) -> None:
        """First call of a training-role program: lower it once and
        hand its per-call cost to the profiler (the exchange program's
        bytes count as collective traffic, not HBM work)."""
        self._cost_done = True
        if self.role not in ("step", "exchange"):
            return
        cost = jit_step_cost(object.__getattribute__(self, "_inner"),
                             *args, **kwargs)
        if cost is not None:
            get_profiler().set_program_cost(
                self.name, self.role, cost["flops"], cost["bytes"],
                source=cost["source"])

    def __call__(self, *args, **kwargs):
        call_idx = self.calls
        self.calls += 1
        # bind the program name for the duration of the dispatch —
        # INCLUDING the first-call cost probe, whose ``lower()`` is
        # what actually traces the function — so collective seams
        # registering into the comm ledger (obs/comm.py
        # register_collective) land on this program, not "untraced"
        from dgl_operator_tpu.obs import comm as _comm
        prev_prog = _comm.set_current_program(self.name)
        try:
            if not self._cost_done:
                try:
                    self._note_cost(args, kwargs)
                except Exception:  # noqa: BLE001 — cost is best-effort
                    pass
            before = self._cache_size()
            t0 = time.perf_counter()
            out = object.__getattribute__(self, "_inner")(*args,
                                                          **kwargs)
            elapsed = time.perf_counter() - t0
        finally:
            _comm.set_current_program(prev_prog)
        after = self._cache_size()
        if before is not None and after is not None and after > before:
            self.compiles += 1
            self._record_compile(call_idx, elapsed)
        if self.role in ("step", "exchange"):
            get_profiler().note_call(self.name)
        return out

    def _record_compile(self, call_idx: int, elapsed: float) -> None:
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        steady = (self.warmup_calls is not None
                  and call_idx >= self.warmup_calls)
        obs.metrics.counter(
            "jit_compiles_total",
            "XLA compiles per instrumented jitted function",
            labels=("fn",)).inc(fn=self.name)
        obs.metrics.histogram(
            "jit_compile_seconds",
            "wall-clock of calls that triggered an XLA compile "
            "(compile + first run)").observe(elapsed)
        obs.events.emit("jit_compile", fn=self.name, call=call_idx,
                        seconds=round(elapsed, 4), steady=steady)


def instrument_jit(name: str, fn, role: Optional[str] = None,
                   warmup_calls: Optional[int] = DEFAULT_WARMUP_CALLS):
    """Wrap a jitted callable with compile/recompile telemetry (and,
    for ``role="step"``/``"exchange"``, cost accounting). ``role=None``
    counts compiles only — serving programs AOT-warm one executable
    per supported shape by design, so their warmup compiles must never
    read as steady-state churn (pass ``warmup_calls=None`` to disable
    the steady flag entirely)."""
    return _InstrumentedJit(name, fn, role=role,
                            warmup_calls=warmup_calls)


# --------------------------------------------------------- watermarks
def device_watermarks_mib() -> Dict[str, float]:
    """Per-device live-buffer high-water MiB. Prefers the backend's
    allocator stats (``memory_stats()['peak_bytes_in_use']`` on real
    TPUs); XLA:CPU has no allocator stats, so the fallback walks the
    live arrays and bills each addressable shard to its device —
    current residency, which the caller maxes into a watermark."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend, no watermark
        return {}
    out: Dict[str, float] = {}
    stats_ok = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats
            stats = None
        if stats:
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use", 0))
            out[str(d)] = round(float(peak) / 2**20, 3)
            stats_ok = True
    if stats_ok:
        return out
    try:
        import jax
        for arr in jax.live_arrays():
            try:
                for shard in arr.addressable_shards:
                    key = str(shard.device)
                    out[key] = out.get(key, 0.0) \
                        + shard.data.nbytes / 2**20
            except Exception:  # noqa: BLE001 — deleted mid-walk
                continue
    except Exception:  # noqa: BLE001 — telemetry never raises
        return {}
    return {k: round(v, 3) for k, v in out.items()}


# ----------------------------------------------------- the profiler
class StepProfiler:
    """Per-process MFU/roofline accounting, fed by the trainers'
    heartbeat. Programs report per-call cost + call counts through
    :func:`instrument_jit`; :meth:`on_heartbeat` turns the window's
    deltas into ``train_mfu`` / ``train_roofline_frac{bound}`` gauges,
    samples the HBM watermark, and emits the Chrome counter tracks.
    Disabled (a cheap no-op) until :meth:`configure` runs."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 window_s: float = 5.0, maxlen: int = 512):
        self._lock = threading.Lock()
        self._clock = clock
        self.window_s = float(window_s)
        self._maxlen = maxlen
        self.enabled = False
        self.peaks: Dict = {}
        self.fallback_cost: Optional[Dict] = None
        self.predicted_hbm_mib: Optional[float] = None
        # name -> {"role", "flops", "bytes", "calls", "source"}
        self._programs: Dict[str, Dict] = {}
        # (ts, step, flops_done, bytes_done, comm_done) snapshots
        self._ticks: List[tuple] = []
        self._wm_ts = 0.0
        self.watermark_mib: Dict[str, float] = {}
        self.last: Dict = {}
        self.flops_scale = 1.0

    # -- configuration (trainers) -------------------------------------
    def configure(self, peaks: Optional[Dict] = None,
                  fallback_cost: Optional[Dict] = None,
                  predicted_hbm_mib: Optional[float] = None,
                  flops_scale: float = 1.0) -> None:
        """``flops_scale`` multiplies every program's per-call cost —
        the dp trainer's SPMD module is costed per shard, so the whole
        job's work is per-shard x dp width."""
        with self._lock:
            self.peaks = peaks or resolve_peaks()
            if fallback_cost is not None:
                self.fallback_cost = fallback_cost
            if predicted_hbm_mib is not None:
                self.predicted_hbm_mib = float(predicted_hbm_mib)
            self.flops_scale = float(flops_scale)
            self.enabled = True
        from dgl_operator_tpu.obs import get_obs
        m = get_obs().metrics
        m.gauge("prof_peak_flops",
                "roofline peak FLOP/s this run was scored against"
                ).set(self.peaks["peak_flops"])
        m.gauge("prof_peak_hbm_gbps",
                "roofline peak HBM GB/s this run was scored against"
                ).set(self.peaks["peak_hbm_gbps"])
        if self.predicted_hbm_mib is not None:
            m.gauge("train_hbm_predicted_mib",
                    "analytic per-device HBM bill of the active config"
                    ).set(self.predicted_hbm_mib)

    def set_program_cost(self, name: str, role: str, flops: float,
                         nbytes: float, source: str = "xla") -> None:
        with self._lock:
            prog = self._programs.setdefault(
                name, {"role": role, "calls": 0})
            prog.update(flops=float(flops), bytes=float(nbytes),
                        source=source)

    def note_call(self, name: str) -> None:
        with self._lock:
            prog = self._programs.setdefault(
                name, {"role": "step", "calls": 0})
            prog["calls"] += 1

    # -- accounting ----------------------------------------------------
    def _totals(self) -> Tuple[float, float, float]:
        """(flops, hbm bytes, comm bytes) completed so far, from the
        instrumented programs' call counts x per-call costs. A step
        program without an XLA cost uses the analytic fallback."""
        fb = self.fallback_cost or {}
        flops = hbm = comm = 0.0
        for prog in self._programs.values():
            calls = prog["calls"]
            if not calls:
                continue
            f = prog.get("flops", fb.get("flops") if
                         prog["role"] == "step" else None)
            b = prog.get("bytes", fb.get("bytes") if
                         prog["role"] == "step" else None)
            if prog["role"] == "exchange":
                comm += calls * (b or 0.0)
            else:
                flops += calls * (f or 0.0)
                hbm += calls * (b or 0.0)
        k = self.flops_scale
        return flops * k, hbm * k, comm * k

    def cost_source(self) -> Optional[str]:
        with self._lock:
            for prog in self._programs.values():
                if prog["role"] == "step" and "flops" in prog:
                    return prog.get("source", "xla")
            return "analytic" if self.fallback_cost else None

    def on_heartbeat(self, step: int) -> Optional[Dict]:
        """One profiler tick per trainer heartbeat: append the totals
        snapshot, derive the rolling-window MFU/roofline, refresh the
        watermark (rate-limited), set the gauges and counter tracks.
        Returns ``{"mfu", "hbm_mib"}`` for the live feed, or ``None``
        while unconfigured / before the window has two edges."""
        if not self.enabled:
            return None
        now = self._clock()
        with self._lock:
            flops, hbm, comm = self._totals()
            self._ticks.append((now, int(step), flops, hbm, comm))
            if len(self._ticks) > self._maxlen:
                del self._ticks[: len(self._ticks) - self._maxlen]
            window = [t for t in self._ticks
                      if t[0] >= now - self.window_s]
            if len(window) < 2:
                window = self._ticks[-2:]
            peaks = dict(self.peaks)
            predicted = self.predicted_hbm_mib
        self._sample_watermark(now)
        if len(window) < 2:
            return None
        t0, s0, f0, b0, c0 = window[0]
        t1, s1, f1, b1, c1 = window[-1]
        dt = t1 - t0
        if dt <= 0 or s1 <= s0:
            return None
        compute = (f1 - f0) / dt / max(peaks["peak_flops"], 1.0)
        peak_bw = max(peaks["peak_hbm_gbps"], 1e-9) * 1e9
        memory = (b1 - b0) / dt / peak_bw
        comm_frac = (c1 - c0) / dt / peak_bw
        fracs = {"compute": compute, "memory": memory,
                 "comm": comm_frac}
        bound = max(fracs, key=fracs.get)
        wm = max(self.watermark_mib.values(), default=0.0)
        out = {"mfu": round(compute, 6), "bound": bound,
               "fracs": fracs, "hbm_mib": round(wm, 3),
               "step_rate_hz": round((s1 - s0) / dt, 4)}
        self.last = out
        self._emit(out, predicted)
        return out

    def _sample_watermark(self, now: float,
                          min_period_s: float = 0.25) -> None:
        if now - self._wm_ts < min_period_s and self.watermark_mib:
            return
        self._wm_ts = now
        for dev, mib in device_watermarks_mib().items():
            if mib > self.watermark_mib.get(dev, 0.0):
                self.watermark_mib[dev] = mib

    def _emit(self, out: Dict, predicted: Optional[float]) -> None:
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        m = obs.metrics
        m.gauge("train_mfu",
                "model-FLOPs utilization of the rolling heartbeat "
                "window (achieved FLOP/s over peak_flops)"
                ).set(out["mfu"])
        g = m.gauge("train_roofline_frac",
                    "fraction of the per-resource peak achieved in the "
                    "window; the max label is the binding resource",
                    labels=("bound",))
        for k, v in out["fracs"].items():
            g.set(round(v, 6), bound=k)
        wm = m.gauge("train_hbm_watermark_mib",
                     "per-device live-buffer high-water MiB",
                     labels=("device",))
        for dev, mib in self.watermark_mib.items():
            wm.set(mib, device=dev)
        # Chrome counter tracks: utilization under the span tree
        obs.tracer.counter("MFU", {"mfu": round(out["mfu"], 6)})
        track = {"watermark": out["hbm_mib"]}
        if predicted is not None:
            track["predicted"] = round(predicted, 3)
        obs.tracer.counter("HBM MiB", track)

    def reset(self) -> None:
        with self._lock:
            self.enabled = False
            self.peaks = {}
            self.fallback_cost = None
            self.predicted_hbm_mib = None
            self._programs.clear()
            self._ticks.clear()
            self.watermark_mib = {}
            self.last = {}
            self.flops_scale = 1.0


_profiler: Optional[StepProfiler] = None
_profiler_lock = threading.Lock()


def get_profiler() -> StepProfiler:
    """The process-global profiler (trainers configure it; the shared
    heartbeat ticks it)."""
    global _profiler
    with _profiler_lock:
        if _profiler is None:
            _profiler = StepProfiler()
        return _profiler


def reset_profiler() -> None:
    """Fresh profiler (tests; a driver starting a second run)."""
    global _profiler
    with _profiler_lock:
        _profiler = None


# ------------------------------------------------- summaries + diff
def _merged_metrics(obs_dir: str) -> Dict:
    from dgl_operator_tpu.obs._io import read_json
    from dgl_operator_tpu.obs.collect import METRICS_JSON, job_dir_of
    for d in (job_dir_of(obs_dir), obs_dir):
        data = read_json(os.path.join(d, METRICS_JSON), {})
        if data.get("merged") or data.get("procs"):
            merged = data.get("merged")
            if merged:
                return merged
            from dgl_operator_tpu.obs.metrics import merge_snapshots
            procs = data.get("procs") or {}
            return merge_snapshots(procs[p] for p in sorted(procs))
    return {}


def _gauge_value(merged: Dict, name: str, **labels) -> Optional[float]:
    fam = merged.get(name) or {}
    best = None
    for s in fam.get("samples", []):
        if labels and any(s.get("labels", {}).get(k) != v
                          for k, v in labels.items()):
            continue
        best = float(s["value"]) if best is None \
            else max(best, float(s["value"]))
    return best


def _counter_total(merged: Dict, name: str) -> float:
    fam = merged.get(name) or {}
    return float(sum(s.get("value", 0)
                     for s in fam.get("samples", [])))


def prof_summary(obs_dir: str) -> Optional[Dict]:
    """The pinned prof keys (``benchkeys.PROF_KEYS``) of a finished or
    running obs dir, read from the job view's merged metrics (plain
    obs dirs merge their own procs). ``None`` when the run carried no
    utilization telemetry at all — pre-prof runs diff as absent, not
    as zero."""
    merged = _merged_metrics(obs_dir)
    mfu = _gauge_value(merged, "train_mfu")
    if mfu is None:
        return None
    fracs = {}
    for s in (merged.get("train_roofline_frac") or {}).get(
            "samples", []):
        fracs[s.get("labels", {}).get("bound", "?")] = float(s["value"])
    bound = max(fracs, key=fracs.get) if fracs else None
    out = {
        "train_mfu": mfu,
        "roofline_bound": bound,
        "roofline_frac": (fracs.get(bound) if bound else None),
        "train_seeds_per_sec": _gauge_value(merged,
                                            "train_seeds_per_sec"),
        "hbm_watermark_mib": _gauge_value(merged,
                                          "train_hbm_watermark_mib"),
        "hbm_predicted_mib": _gauge_value(merged,
                                          "train_hbm_predicted_mib"),
        "jit_compiles": int(_counter_total(merged,
                                           "jit_compiles_total")),
    }
    assert tuple(out) == PROF_KEYS, (tuple(out), PROF_KEYS)
    out["peak_flops"] = _gauge_value(merged, "prof_peak_flops")
    out["peak_hbm_gbps"] = _gauge_value(merged, "prof_peak_hbm_gbps")
    return out


# the keys the regression gate compares (higher is better); the rest
# of PROF_KEYS ride along for the report
GATED_KEYS = ("train_mfu", "train_seeds_per_sec")


def diff_summaries(run: Dict, baseline: Dict,
                   margin: float = DEFAULT_DIFF_MARGIN) -> Dict:
    """Compare a run's prof summary against a baseline under an
    adoption margin: a gated key regresses when the run falls below
    ``baseline * (1 - margin)``; a gated key the baseline has but the
    run lost entirely is also a regression (silently dropped telemetry
    must not pass a perf gate). Returns ``{"ok", "margin",
    "regressions", "compared"}``."""
    regressions: List[Dict] = []
    compared: Dict[str, Dict] = {}
    for key in GATED_KEYS:
        base = baseline.get(key)
        cur = run.get(key)
        if base is None or base <= 0:
            continue
        floor = base * (1.0 - margin)
        entry = {"run": cur, "baseline": base,
                 "floor": round(floor, 6)}
        compared[key] = entry
        if cur is None or cur < floor:
            regressions.append({"key": key, **entry})
    return {"ok": not regressions, "margin": margin,
            "regressions": regressions, "compared": compared}


def _load_summary(path: str) -> Dict:
    """A diff operand: an obs directory, a raw summary JSON, or a
    tracked PROF.json record (``{"prof": {...}}``)."""
    if os.path.isdir(path):
        summary = prof_summary(path)
        if summary is None:
            raise ValueError(f"{path}: no prof telemetry in the obs "
                             "view (did the run emit train_mfu?)")
        return summary
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("prof"), dict):
        return data["prof"]
    if isinstance(data, dict):
        return data
    raise ValueError(f"{path}: not a prof summary")


def render_summary(summary: Dict) -> str:
    lines = ["tpu-prof"]
    lines.append(f"  MFU        : {summary['train_mfu']:.4f}"
                 + (f"  (peak {summary['peak_flops']:.3g} FLOP/s)"
                    if summary.get("peak_flops") else ""))
    if summary.get("roofline_bound"):
        lines.append(f"  roofline   : {summary['roofline_bound']}-bound"
                     f" at {summary['roofline_frac']:.4f} of peak")
    if summary.get("train_seeds_per_sec") is not None:
        lines.append(f"  throughput : "
                     f"{summary['train_seeds_per_sec']:.1f} seeds/s")
    if summary.get("hbm_watermark_mib") is not None:
        line = f"  HBM        : {summary['hbm_watermark_mib']:.1f} MiB" \
            " watermark"
        if summary.get("hbm_predicted_mib") is not None:
            line += f" vs {summary['hbm_predicted_mib']:.1f} predicted"
        lines.append(line)
    lines.append(f"  compiles   : {summary.get('jit_compiles', 0)}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-prof",
        description="Hardware-utilization introspection: render a "
                    "run's MFU/roofline/HBM summary, or diff two runs "
                    "as a perf-regression gate.")
    sub = ap.add_subparsers(dest="cmd")
    rep = sub.add_parser("report", help="render a run's prof summary")
    rep.add_argument("obs_dir")
    rep.add_argument("--json", action="store_true")
    dif = sub.add_parser(
        "diff", help="compare a run against a baseline (rc 1 when a "
                     "gated key regresses past the margin)")
    dif.add_argument("run", help="obs dir, summary JSON, or PROF.json")
    dif.add_argument("baseline", help="same forms as the run operand")
    dif.add_argument("--margin", type=float,
                     default=DEFAULT_DIFF_MARGIN,
                     help="adoption margin (fraction below baseline "
                          "that still passes)")
    # bare `tpu-prof <obs-dir>` reads as a report (the subparser would
    # otherwise reject the path as an invalid choice)
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] not in ("report", "diff", "-h", "--help"):
        argv = ["report", *argv]
    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2
    try:
        if args.cmd == "report":
            summary = _load_summary(args.obs_dir)
            print(json.dumps(summary, indent=2, sort_keys=True)
                  if args.json else render_summary(summary))
            return 0
        run = _load_summary(args.run)
        baseline = _load_summary(args.baseline)
    except (OSError, ValueError) as exc:
        print(f"tpu-prof: {exc}", file=sys.stderr)
        return 2
    result = diff_summaries(run, baseline, margin=args.margin)
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
