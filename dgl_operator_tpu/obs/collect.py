"""Job-level telemetry collection: pull every worker's obs artifacts
back over the exec/copy fabric and fold them into ONE ``obs/job/``
view.

PR 4 gave every process events/metrics/traces, but each host's
``obs/`` directory is an island — the reference's only cross-host
visibility is ``kubectl exec`` / ``kubectl cp`` by hand. The collector
closes that gap with the same two verbs: :func:`collect_job` fetches
each host's artifact files (``Fabric.fetch`` — the pull direction of
the copy verb, so the chaos and retry layers wrapped around the fabric
cover collection exactly like any other data-plane call) into
``obs/job/hosts/<host>/`` and then merges them:

- ``obs/job/events.jsonl`` — one event timeline ordered across hosts
  (exact-duplicate records collapse, so hosts sharing one filesystem —
  the LocalFabric case — contribute each record once);
- ``obs/job/metrics.json`` — every process's snapshot under ``procs``,
  a per-host merged view under ``hosts``, and the global ``merged``
  view (rendered to ``obs/job/metrics.prom``);
- ``obs/job/trace.json`` — a single Chrome trace: per-source pid
  remapping keeps one process row per (host, pid) even when real
  hosts' pids collide, with ``process_name`` metadata labeling each
  row by its origin.

Collection is best-effort per host: a lost host's missing artifacts
are recorded in the returned manifest (and surface as findings in
``obs/analyze.py``), never raised — telemetry must not fail the job.

Stdlib-only (the fabric is imported lazily and only when the caller
passes none) — the analytics and doctor layers import this module.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from dgl_operator_tpu.obs._io import atomic_write, read_json
from dgl_operator_tpu.obs.events import EVENTS_JSONL
from dgl_operator_tpu.obs.metrics import (METRICS_JSON, METRICS_PROM,
                                          merge_snapshots,
                                          render_prometheus)
from dgl_operator_tpu.obs.trace import TRACE_JSON

JOB_SUBDIR = "job"
HOSTS_SUBDIR = "hosts"
ARTIFACTS = (EVENTS_JSONL, METRICS_JSON, METRICS_PROM, TRACE_JSON)


def job_dir_of(obs_dir: str) -> str:
    return os.path.join(obs_dir, JOB_SUBDIR)


# ----------------------------------------------------------- collection
def collect_job(obs_dir: str, hosts: Sequence[str], fabric=None,
                remote_dir: Optional[str] = None,
                container: Optional[str] = None) -> Dict:
    """Fetch every host's obs artifacts into
    ``<obs_dir>/job/hosts/<host>/`` and merge them into the job view.
    ``remote_dir`` is the obs directory path on the workers (defaults
    to ``obs_dir`` — the operator stages the same workspace path in
    every pod). Returns a manifest: per-host fetched/missing artifacts
    plus the merge summary."""
    if fabric is None:
        from dgl_operator_tpu.launcher.fabric import get_fabric
        fabric = get_fabric()
    remote_dir = remote_dir or obs_dir
    job_dir = job_dir_of(obs_dir)
    manifest: Dict = {"job_dir": job_dir, "hosts": {}}
    sources: List[Tuple[str, str]] = []
    for host in hosts:
        hdir = os.path.join(job_dir, HOSTS_SUBDIR, host)
        os.makedirs(hdir, exist_ok=True)
        rec: Dict = {"fetched": [], "errors": {}}
        for name in ARTIFACTS:
            try:
                fabric.fetch(host, os.path.join(remote_dir, name), hdir,
                             container=container)
                rec["fetched"].append(name)
            except Exception as exc:  # noqa: BLE001 — per-host record
                rec["errors"][name] = str(exc)[:300]
        manifest["hosts"][host] = rec
        if rec["fetched"]:
            sources.append((host, hdir))
    manifest.update(merge_job_view(job_dir, sources=sources))
    atomic_write(os.path.join(job_dir, "manifest.json"),
                 json.dumps(manifest, indent=2, sort_keys=True))
    return manifest


# ---------------------------------------------------------------- merge
def merge_job_view(job_dir: str,
                   sources: Optional[Sequence[Tuple[str, str]]] = None
                   ) -> Dict:
    """Merge ``(label, directory)`` sources — per-host fetches, or a
    single local obs dir — into the job view under ``job_dir``.
    Defaults to the directories under ``<job_dir>/hosts/``."""
    if sources is None:
        hroot = os.path.join(job_dir, HOSTS_SUBDIR)
        names = sorted(os.listdir(hroot)) if os.path.isdir(hroot) else []
        sources = [(n, os.path.join(hroot, n)) for n in names
                   if os.path.isdir(os.path.join(hroot, n))]
    os.makedirs(job_dir, exist_ok=True)
    docs = _read_trace_docs(sources)
    offsets = _trace_clock_offsets(docs)
    n_events, run_id = _merge_events(job_dir, sources, offsets)
    n_procs = _merge_metrics(job_dir, sources, run_id)
    n_trace = _merge_trace(job_dir, docs, offsets)
    return {"sources": [label for label, _ in sources],
            "run": run_id, "events": n_events, "procs": n_procs,
            "trace_events": n_trace,
            "clock_offsets_us": {k: round(v, 1)
                                 for k, v in offsets.items()}}


def _merge_events(job_dir, sources, offsets=None
                  ) -> Tuple[int, Optional[str]]:
    """One timeline across hosts: parse every source's events.jsonl,
    drop exact duplicates (hosts sharing a filesystem fetch the same
    file), clock-align each source by the trace-derived offset (the
    xray's heartbeat step windows must live on the same clock as the
    aligned trace spans), stable-sort by timestamp."""
    seen = set()
    records: List[Dict] = []
    run_id = None
    for label, d in sources:
        # offsets are trace µs; event timestamps are epoch seconds
        off_s = (offsets or {}).get(label, 0.0) / 1e6
        path = os.path.join(d, EVENTS_JSONL)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line or line in seen:
                continue
            seen.add(line)
            try:
                rec = json.loads(line)
            except ValueError:
                continue   # torn tail line of a killed writer
            if isinstance(rec, dict):
                if off_s and isinstance(rec.get("ts"), (int, float)):
                    rec["ts"] = float(rec["ts"]) + off_s
                records.append(rec)
                if run_id is None and rec.get("run"):
                    run_id = rec["run"]
    records.sort(key=lambda r: (r.get("ts") or 0.0))
    atomic_write(os.path.join(job_dir, EVENTS_JSONL),
                 "".join(json.dumps(r, default=str) + "\n"
                         for r in records))
    return len(records), run_id


def _merge_metrics(job_dir, sources, run_id) -> int:
    """Global ``merged`` + per-host merged series + every process's
    snapshot. Procs are keyed ``host:pid:role`` already, so shared-dir
    duplicates collapse by key."""
    procs: Dict[str, dict] = {}
    hosts_view: Dict[str, dict] = {}
    for label, d in sources:
        data = read_json(os.path.join(d, METRICS_JSON), {})
        sprocs = data.get("procs") or {}
        if not isinstance(sprocs, dict):
            continue
        procs.update(sprocs)
        if sprocs:
            hosts_view[label] = merge_snapshots(
                sprocs[p] for p in sorted(sprocs))
    merged = merge_snapshots(procs[p] for p in sorted(procs))
    atomic_write(os.path.join(job_dir, METRICS_JSON), json.dumps(
        {"run": run_id, "hosts": hosts_view, "procs": procs,
         "merged": merged}, indent=2, sort_keys=True))
    atomic_write(os.path.join(job_dir, METRICS_PROM),
                 render_prometheus(merged))
    return len(procs)


def _trace_clock_offsets(docs: Sequence[Tuple[str, List[Dict]]]
                         ) -> Dict[str, float]:
    """Per-source clock offset (µs to ADD to every timestamp of the
    source) estimated from matched phase-barrier anchors. Hosts stamp
    spans on their own wall clocks, so raw cross-host merge order is
    wrong under skew — and any critical path read from it is fiction.

    The anchors are the driver's ``export_env`` phase spans
    (cat="tpurun", launcher/tpurun.py): the driver publishes its span
    ids into the environment of every subprocess it spawns inside the
    span, so a trainer span whose ``parent_id`` matches an anchor from
    a DIFFERENT source is causally fenced by it — the child cannot
    start before its parent started, nor end after its parent ended.
    An observed violation is provable skew; the correction is the
    minimal shift restoring both bounds (0 when causality already
    holds, so zero-skew runs — and the doctor's single-source local
    path — merge byte-identically to the pre-alignment behavior)."""
    anchors: Dict[str, Tuple[str, float, float]] = {}
    for label, evs in docs:
        for ev in evs:
            if ev.get("ph") != "X" or ev.get("cat") != "tpurun":
                continue
            sid = (ev.get("args") or {}).get("span_id")
            if sid and isinstance(ev.get("ts"), (int, float)):
                anchors[sid] = (label, float(ev["ts"]),
                                float(ev["ts"]) + float(ev.get("dur")
                                                        or 0.0))
    offsets: Dict[str, float] = {label: 0.0 for label, _ in docs}
    for label, evs in docs:
        lo = hi = None
        for ev in evs:
            if ev.get("ph") != "X" \
                    or not isinstance(ev.get("ts"), (int, float)):
                continue
            a = anchors.get((ev.get("args") or {}).get("parent_id"))
            if a is None or a[0] == label:
                continue       # only FOREIGN anchors carry skew signal
            s = float(ev["ts"])
            e = s + float(ev.get("dur") or 0.0)
            lo = max(lo, a[1] - s) if lo is not None else a[1] - s
            hi = min(hi, a[2] - e) if hi is not None else a[2] - e
        if lo is None:
            continue
        if lo > 0:             # host clock behind the driver's
            offsets[label] = lo
        elif hi is not None and hi < 0:   # host clock ahead
            offsets[label] = hi
    return offsets


def _read_trace_docs(sources) -> List[Tuple[str, List[Dict]]]:
    docs: List[Tuple[str, List[Dict]]] = []
    for label, d in sources:
        doc = read_json(os.path.join(d, TRACE_JSON), {})
        docs.append((label, [ev for ev in doc.get("traceEvents", [])
                             if isinstance(ev, dict)]))
    return docs


def _merge_trace(job_dir, docs, offsets) -> int:
    """One Chrome trace for the whole job. Events dedupe on exact
    content; surviving events remap pid by (origin source, pid) so two
    hosts' colliding pids get separate process rows, each labeled by a
    ``process_name`` metadata record carrying its origin. Timestamps
    are clock-aligned per source (:func:`_trace_clock_offsets`) during
    the remap."""
    seen = set()
    pid_map: Dict[Tuple[str, object], int] = {}
    named = set()
    out: List[Dict] = []
    extra_meta: List[Dict] = []

    def mapped(label, opid) -> int:
        key = (label, opid)
        if key not in pid_map:
            pid_map[key] = len(pid_map) + 1
        return pid_map[key]

    for label, evs in docs:
        off = offsets.get(label, 0.0)
        for ev in evs:
            # dedupe on the RAW record: hosts sharing one filesystem
            # fetch the same file under every label, and the copies
            # must collapse before any per-label offset can fork them
            key = json.dumps(ev, sort_keys=True, default=str)
            if key in seen:
                continue
            seen.add(key)
            ev = dict(ev)
            opid = ev.get("pid")
            ev["pid"] = mapped(label, opid)
            if off and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(float(ev["ts"]) + off, 1)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                args = dict(ev.get("args") or {})
                args["name"] = f"{label}/{args.get('name', opid)}"
                ev["args"] = args
                named.add(ev["pid"])
            out.append(ev)
    for (label, opid), pid in sorted(pid_map.items(),
                                     key=lambda kv: kv[1]):
        if pid not in named:
            extra_meta.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": f"{label}/pid {opid}"}})
    doc = {"traceEvents": extra_meta + out, "displayTimeUnit": "ms"}
    atomic_write(os.path.join(job_dir, TRACE_JSON),
                 json.dumps(doc, indent=1))
    return len(out)
