"""``tpu-top`` — a refreshing per-host/per-role console view of a
running job.

The doctor diagnoses a run after the fact; ``tpu-top`` answers the
operator's live question — which worker is slow *right now* — by
polling the run's registered live sidecars (``<obs_dir>/live/`` →
``GET /livez``, :mod:`~.live`) and rendering one row per process:
step, step rate, heartbeat rate, qps, p50/p99 latency, halo-exchange
MiB/s, stall fraction, SLO state, and — when the run carries the
utilization profiler (obs/prof.py) — rolling MFU and the HBM
watermark. Workers without a reachable
sidecar fall back to the file plane (events.jsonl heartbeats — the
:func:`~.analyze.job_health` signal), marked ``file`` in the source
column so the operator knows how fresh the row is.

Usage::

    tpu-top [<obs-dir>] [--once] [--interval 2.0]
    python -m dgl_operator_tpu.obs.top --workspace ws --once

Exit status: 0 (``--once``: also when the view rendered but carried no
workers — an empty job is not an error), 2 on usage errors.

Stdlib-only — runs in the control-plane image.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

from dgl_operator_tpu.obs import OBS_DIR_ENV
from dgl_operator_tpu.obs.live import fetch_livez, live_endpoints

_COLUMNS = ("worker", "src", "state", "step", "loss", "gnorm",
            "step/s", "hb/s",
            "qps", "p50ms", "p99ms", "exMiB/s", "comMiB/s", "stall%",
            "ovl", "mfu", "hbmMiB", "crit")


def _fmt(v, nd: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _row_from_livez(snap: Dict) -> Dict:
    slo = snap.get("slo") or {}
    if snap.get("done"):
        state = "done"
    elif snap.get("shedding"):
        state = "SHED"
    elif slo and not slo.get("ok", True):
        state = "SLO!"
    else:
        state = "ok"
    stall = snap.get("stall_frac")
    return {
        "worker": f"{snap.get('host', '?')}:{snap.get('pid', '?')}:"
                  f"{snap.get('role', '?')}",
        "src": "live", "state": state,
        "step": snap.get("step"),
        # model-health columns (obs/quality.py riders on the live feed)
        "loss": snap.get("loss"),
        "gnorm": snap.get("grad_norm"),
        "step/s": snap.get("step_rate_hz"),
        "hb/s": snap.get("heartbeat_hz"),
        "qps": snap.get("qps"),
        "p50ms": snap.get("p50_ms"),
        "p99ms": snap.get("p99_ms"),
        "exMiB/s": snap.get("exchange_mib_per_s"),
        # watched-collective rate over ALL mesh axes (obs/comm.py
        # axis_bytes_total rider; the per-axis dict stays on /livez
        # as comm_axis_mib_per_s for drill-down)
        "comMiB/s": snap.get("comm_mib_per_s"),
        "stall%": (round(stall * 100, 1) if stall is not None
                   else None),
        "ovl": snap.get("overlap_ratio"),
        "mfu": snap.get("mfu"),
        "hbmMiB": snap.get("hbm_mib"),
        # dominant critical-path category over the rolling window
        # (obs/xray.py live_critpath rider on the live feed),
        # rendered "cat:frac" — the glanceable "what is this worker
        # spending its step on" column
        "crit": _crit_cell(snap.get("critpath_frac")),
    }


def _crit_cell(fracs: Optional[Dict]) -> Optional[str]:
    if not isinstance(fracs, dict) or not fracs:
        return None
    cat = max(fracs, key=fracs.get)
    return f"{cat}:{fracs[cat]:.2f}"


def _rows_from_files(obs_dir: str, seen: set) -> List[Dict]:
    """File-plane fallback rows for workers with no live sidecar: the
    events.jsonl heartbeat signal (``job_health``)."""
    from dgl_operator_tpu.obs.analyze import job_health
    rows: List[Dict] = []
    for w, rec in job_health(obs_dir).get("workers", {}).items():
        if w in seen:
            continue
        rows.append({"worker": w, "src": "file",
                     "state": rec.get("status", "?"),
                     "step": rec.get("last_step"),
                     "loss": None, "gnorm": None,
                     "step/s": None, "hb/s": None, "qps": None,
                     "p50ms": None, "p99ms": None, "exMiB/s": None,
                     "comMiB/s": None, "stall%": None, "ovl": None,
                     "mfu": None, "hbmMiB": None, "crit": None})
    return rows


def gather_rows(obs_dir: str, timeout: float = 1.0) -> List[Dict]:
    """One refresh: every reachable live endpoint becomes a live row;
    everyone else the file plane still knows about rides along."""
    rows: List[Dict] = []
    seen: set = set()
    for ep in live_endpoints(obs_dir):
        snap = fetch_livez(ep, timeout=timeout)
        if snap is None:
            continue
        row = _row_from_livez(snap)
        rows.append(row)
        seen.add(row["worker"])
    rows.extend(_rows_from_files(obs_dir, seen))
    rows.sort(key=lambda r: (r["src"] != "live", r["worker"]))
    return rows


def render(rows: List[Dict], obs_dir: str) -> str:
    widths = {c: len(c) for c in _COLUMNS}
    table = []
    for r in rows:
        cells = {c: _fmt(r.get(c)) for c in _COLUMNS}
        for c, v in cells.items():
            widths[c] = max(widths[c], len(v))
        table.append(cells)
    lines = [f"tpu-top — {obs_dir}  "
             f"({len(rows)} worker(s), "
             f"{time.strftime('%H:%M:%S')})"]
    lines.append("  ".join(c.ljust(widths[c]) for c in _COLUMNS))
    for cells in table:
        lines.append("  ".join(cells[c].ljust(widths[c])
                               for c in _COLUMNS))
    if not rows:
        lines.append("(no workers yet — is the job running and "
                     "TPU_OPERATOR_LIVE_PORT exported?)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-top",
        description="Live per-host/per-role view of a running job "
                    "(step rate, p99, exchange MiB/s, SLO state) from "
                    "the obs live sidecars, file-plane fallback.")
    ap.add_argument("obs_dir", nargs="?", default=None,
                    help="obs directory (default: $TPU_OPERATOR_OBS_DIR"
                         ", else <workspace>/obs)")
    ap.add_argument("--workspace", default=None,
                    help="workspace whose obs/ subdir to watch")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI / scripts)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    ap.add_argument("--timeout", type=float, default=1.0,
                    help="per-endpoint /livez timeout")
    ap.add_argument("--json", action="store_true",
                    help="emit the rows as JSON instead of a table")
    args = ap.parse_args(argv)
    obs_dir = (args.obs_dir or os.environ.get(OBS_DIR_ENV)
               or (os.path.join(args.workspace, "obs")
                   if args.workspace else None))
    if not obs_dir:
        ap.error("no obs directory: pass one, set "
                 f"{OBS_DIR_ENV}, or use --workspace")
    obs_dir = os.path.abspath(obs_dir)
    if not os.path.isdir(obs_dir):
        print(f"tpu-top: no such obs directory: {obs_dir}",
              file=sys.stderr)
        return 2
    while True:
        rows = gather_rows(obs_dir, timeout=args.timeout)
        if args.json:
            print(json.dumps({"obs_dir": obs_dir, "rows": rows}))
        else:
            frame = render(rows, obs_dir)
            if not args.once:
                # clear + home, full-screen refresh (plain ANSI; tput
                # would drag in a terminfo dependency)
                frame = "\x1b[2J\x1b[H" + frame
            print(frame, flush=True)
        if args.once:
            return 0
        try:
            time.sleep(max(args.interval, 0.2))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
