"""Communication observability plane — the per-collective ledger, the
ICI/DCN network roofline, and the one generalized in-flight watcher.

PR 12's roofline bills whole programs and PRs 14/16 measured overlap
with two ad-hoc watchers (``tpu-pipewatch``, ``tpu-z3watch``) that each
knew about exactly one collective. This module gives every in-program
collective seam a first-class record and one watcher that turns those
records into telemetry:

- **Ledger** (:class:`CommLedger`): each collective seam —
  ``halo_row_lookup`` / ``alltoall_*`` / ``halo_all_to_all``
  (parallel/halo.py), grad ``pmean`` / ``psum_scatter`` and the ZeRO-3
  ``param_allgather`` (parallel/dp.py), the embedding ring and a2a
  lookups (parallel/ring.py, parallel/embedding.py) — calls
  :func:`register_collective` at TRACE time with its op kind, mesh
  axis, analytic bytes from the existing byte models, and fused-depth
  K. Registration is deliberately obs-free (TPU001: traced code must
  not emit telemetry): one locked dict write, keyed by
  ``(program, op, axis)`` so retraces overwrite idempotently. The
  owning program name comes from :func:`current_program`, set by
  ``prof.instrument_jit`` around every instrumented dispatch.
- **Network roofline** (:func:`resolve_link_peaks`): the ``comm`` knob
  layer (``peak_ici_gbps`` / ``peak_dcn_gbps``, autotune/knobs.py)
  resolved exactly like the PR 12 compute peaks — tuned manifest →
  config → env (``TPU_OPERATOR_PEAK_ICI_GBPS`` /
  ``TPU_OPERATOR_PEAK_DCN_GBPS``) → per-generation auto-detect —
  giving the roofline a per-axis *network* dimension: achieved GB/s
  per collective scored against the link its mesh axis rides
  (:func:`link_of`).
- **Watcher** (:class:`CommWatcher`): the single ``tpu-commwatch``
  thread replacing both legacy watchers (which are thin aliases now,
  runtime/dist.py). ``watch()`` submits one completed dispatch; the
  observe body ONLY blocks on readiness (TPU002: watch threads never
  launch collectives) and then emits per-collective Chrome spans
  (cat=comm), ``comm_bytes_total{op,axis}`` / ``comm_seconds{op,axis}``
  counters, achieved-vs-peak ``comm_link_gbps`` / ``comm_link_util``
  gauges, per-slot ``comm_slot_seconds`` skew for collective-
  granularity straggler findings (obs/analyze.py), and start/done
  flight-recorder samples so a crash names the collective in flight
  (obs/flight.py).

Import-time stdlib-only (jax is imported lazily inside the watcher) so
the CLIs stay light.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Optional, Tuple

from dgl_operator_tpu.benchkeys import COMM_KEYS

PEAK_ICI_ENV = "TPU_OPERATOR_PEAK_ICI_GBPS"
PEAK_DCN_ENV = "TPU_OPERATOR_PEAK_DCN_GBPS"

WATCH_THREAD_PREFIX = "tpu-commwatch"

# Per-generation link peaks (GB/s), matched by substring against
# jax.devices()[0].device_kind like prof._DEVICE_PEAKS: per-chip
# aggregate ICI bandwidth of the generation's torus links, and the
# per-host DCN NIC share. Indicative roofline denominators, not
# datasheet law — override via the comm knob layer or the env vars.
_LINK_PEAKS = (
    ("v5e", 186.0, 25.0),
    ("v5p", 600.0, 25.0),
    ("v4", 300.0, 25.0),
    ("v3", 224.0, 12.5),
    ("v2", 124.0, 12.5),
)
# CPU fallback: loopback "links" so utilization gauges stay meaningful
# on the 8-device virtual mesh the test/smoke tier runs on.
_CPU_ICI_GBPS = 10.0
_CPU_DCN_GBPS = 1.0


# ------------------------------------------------------------------
# program attribution
# ------------------------------------------------------------------
_tls = threading.local()


def set_current_program(name: Optional[str]) -> Optional[str]:
    """Bind the instrumented program being dispatched on this thread
    (prof._InstrumentedJit wraps its inner call with this) so seam
    registrations during a trace land on the right program. Returns
    the previous binding for restore."""
    prev = getattr(_tls, "program", None)
    _tls.program = name
    return prev


def current_program() -> str:
    """The program currently tracing/dispatching on this thread, or
    ``"untraced"`` for seams exercised outside ``instrument_jit``."""
    return getattr(_tls, "program", None) or "untraced"


# ------------------------------------------------------------------
# the ledger
# ------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class CommOp:
    """One collective seam inside one program: what moves, where."""

    op: str               # op kind, e.g. "halo_a2a_serve", "grad_pmean"
    axis: str             # mesh axis the collective rides
    bytes_per_call: int   # analytic bytes per program dispatch
    program: str          # owning instrumented program
    fused_depth: int = 1  # pipelined depth K (ZeRO-3 gather_depth)


class CommLedger:
    """Trace-time registry of every collective a program contains.
    Keyed by ``(program, op, axis)`` — a retrace of the same program
    overwrites its own records, so steady-state retraces are
    idempotent and bytes never double-count."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ops: Dict[Tuple[str, str, str], CommOp] = {}

    def register(self, rec: CommOp) -> None:
        with self._lock:
            self._ops[(rec.program, rec.op, rec.axis)] = rec

    def ops(self) -> List[CommOp]:
        with self._lock:
            return list(self._ops.values())

    def ops_of(self, program: str) -> List[CommOp]:
        """Every collective registered under one program, largest
        first (the watcher attributes skew to the dominant one)."""
        with self._lock:
            recs = [o for o in self._ops.values()
                    if o.program == program]
        return sorted(recs, key=lambda o: -o.bytes_per_call)

    def bytes_of(self, op: str, axis: Optional[str] = None) -> int:
        """Analytic bytes of one op kind (summed over programs)."""
        with self._lock:
            return sum(o.bytes_per_call for o in self._ops.values()
                       if o.op == op
                       and (axis is None or o.axis == axis))

    def clear(self) -> None:
        with self._lock:
            self._ops.clear()


_ledger: Optional[CommLedger] = None
_ledger_lock = threading.Lock()


def get_ledger() -> CommLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CommLedger()
        return _ledger


def reset_ledger() -> None:
    global _ledger
    with _ledger_lock:
        _ledger = None


def register_collective(op: str, axis: str, nbytes,
                        fused_depth: int = 1) -> None:
    """Record one collective seam at trace time. Deliberately emits
    NOTHING (no metrics/events/spans/clock reads — TPU001 bans
    telemetry inside traced code): just a locked ledger append the
    watcher reads back at run time. Safe to call on every trace; a
    zero-byte record (a seam whose aggregate selected nothing, e.g.
    an all-sharded WUS tree's empty pmean side) is dropped."""
    try:
        rec = CommOp(op=str(op), axis=str(axis),
                     bytes_per_call=int(nbytes),
                     program=current_program(),
                     fused_depth=max(int(fused_depth), 1))
    except (TypeError, ValueError):
        return
    if rec.bytes_per_call <= 0:
        return
    get_ledger().register(rec)


# ------------------------------------------------------------------
# network roofline: the comm knob layer
# ------------------------------------------------------------------
@dataclasses.dataclass
class CommConfig:
    """Link-peak knobs (the ``comm`` layer, autotune/knobs.py).
    0 = resolve from env, else auto-detect per generation."""

    peak_ici_gbps: float = 0.0
    peak_dcn_gbps: float = 0.0


def _detect_link_peaks() -> Dict[str, object]:
    """Per-generation auto-detection, mirroring prof._detect_peaks."""
    import jax

    try:
        dev = jax.devices()[0]
    except RuntimeError:
        return {"peak_ici_gbps": _CPU_ICI_GBPS,
                "peak_dcn_gbps": _CPU_DCN_GBPS, "source": "auto:none"}
    kind = getattr(dev, "device_kind", "") or ""
    if dev.platform == "tpu":
        low = kind.lower()
        for tag, ici, dcn in _LINK_PEAKS:
            if tag in low:
                return {"peak_ici_gbps": ici, "peak_dcn_gbps": dcn,
                        "source": f"auto:{tag}"}
        _, ici, dcn = _LINK_PEAKS[0]
        return {"peak_ici_gbps": ici, "peak_dcn_gbps": dcn,
                "source": "auto:tpu"}
    return {"peak_ici_gbps": _CPU_ICI_GBPS,
            "peak_dcn_gbps": _CPU_DCN_GBPS, "source": "auto:cpu"}


def resolve_link_peaks(
        cfg: Optional[CommConfig] = None) -> Dict[str, object]:
    """Resolve the per-link peak GB/s the utilization gauges score
    against. Same precedence as the PR 12 compute peaks
    (prof.resolve_peaks): tuned manifest → explicit config → env
    (``TPU_OPERATOR_PEAK_ICI_GBPS`` / ``TPU_OPERATOR_PEAK_DCN_GBPS``)
    → per-generation auto-detect. Returns
    ``{"peak_ici_gbps", "peak_dcn_gbps", "source"}``."""
    from dgl_operator_tpu.autotune import knobs

    cfg = knobs.apply_tuned(cfg or CommConfig(), layer="comm")
    knobs.validate("peak_ici_gbps", cfg.peak_ici_gbps)
    knobs.validate("peak_dcn_gbps", cfg.peak_dcn_gbps)
    if cfg.peak_ici_gbps > 0 and cfg.peak_dcn_gbps > 0:
        return {"peak_ici_gbps": float(cfg.peak_ici_gbps),
                "peak_dcn_gbps": float(cfg.peak_dcn_gbps),
                "source": "config"}
    auto: Optional[Dict[str, object]] = None
    out: Dict[str, object] = {}
    sources = []
    for knob, env in (("peak_ici_gbps", PEAK_ICI_ENV),
                      ("peak_dcn_gbps", PEAK_DCN_ENV)):
        val = float(getattr(cfg, knob))
        if val > 0:
            out[knob] = val
            sources.append("config")
            continue
        raw = os.environ.get(env, "").strip()
        if raw:
            try:
                val = float(raw)
            except ValueError:
                val = 0.0
        if val > 0:
            out[knob] = val
            sources.append("env")
            continue
        if auto is None:
            auto = _detect_link_peaks()
        out[knob] = auto[knob]
        sources.append(str(auto["source"]))
    out["source"] = sources[0] if len(set(sources)) == 1 \
        else "+".join(sources)
    return out


def link_of(axis: str) -> str:
    """Which physical link a mesh axis rides: axes named for the
    data-center network (``dcn`` anywhere in the name, the ROADMAP
    item 1 multi-slice convention) score against the DCN peak,
    everything else against ICI."""
    return "dcn" if "dcn" in axis.lower() else "ici"


# ------------------------------------------------------------------
# per-axis byte accumulator (livez / tpu-top rider)
# ------------------------------------------------------------------
_axis_lock = threading.Lock()
_axis_bytes: Dict[str, float] = {}


def _account_axis(axis: str, nbytes: float) -> None:
    with _axis_lock:
        _axis_bytes[axis] = _axis_bytes.get(axis, 0.0) + float(nbytes)


def axis_bytes_total() -> Dict[str, float]:
    """Cumulative watched bytes per mesh axis this process — the
    heartbeat feeds this into /livez so ``tpu-top`` can render a
    per-axis MiB/s column (obs/live.py, obs/top.py)."""
    with _axis_lock:
        return dict(_axis_bytes)


def reset_axis_bytes() -> None:
    with _axis_lock:
        _axis_bytes.clear()


# ------------------------------------------------------------------
# the watcher
# ------------------------------------------------------------------
class CommWatcher:
    """The one in-flight-window watcher (thread prefix
    ``tpu-commwatch``), replacing the copy-pasted ``tpu-pipewatch`` /
    ``tpu-z3watch`` bodies. One FIFO worker preserves submission order
    so windows close in dispatch order; the observe body only blocks
    on readiness and emits — it never launches a program (TPU002).

    ``watch()`` generalizes both legacy call shapes: optional legacy
    spans/timer sinks/overlap trackers ride along with the
    per-collective emission driven by the ledger's records for
    ``program``."""

    def __init__(self, name: str = WATCH_THREAD_PREFIX,
                 peaks: Optional[Dict[str, object]] = None):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix=name)
        self._lock = threading.Lock()
        self._peaks = peaks
        self._peaks_published = False
        self._seq = 0

    # -- link peaks (lazy: resolving may touch jax.devices()) --------
    def _link_peaks(self) -> Dict[str, object]:
        with self._lock:
            peaks = self._peaks
            published = self._peaks_published
        if peaks is None:
            try:
                peaks = resolve_link_peaks()
            except Exception:  # noqa: BLE001 — roofline is best-effort
                peaks = {"peak_ici_gbps": 0.0, "peak_dcn_gbps": 0.0,
                         "source": "none"}
            with self._lock:
                self._peaks = peaks
        if not published:
            try:
                from dgl_operator_tpu.obs import get_obs
                m = get_obs().metrics
                m.gauge("comm_peak_ici_gbps",
                        "resolved ICI link peak GB/s the comm roofline "
                        "scores against").set(
                            float(peaks["peak_ici_gbps"]))
                m.gauge("comm_peak_dcn_gbps",
                        "resolved DCN link peak GB/s the comm roofline "
                        "scores against").set(
                            float(peaks["peak_dcn_gbps"]))
            except Exception:  # noqa: BLE001
                pass
            with self._lock:
                self._peaks_published = True
        return peaks

    # -- submission ---------------------------------------------------
    def watch(self, ref, t0: float, *, step=None,
              spans: Iterable[Tuple[str, str]] = (),
              timers: Iterable[Tuple[object, str]] = (),
              compute: Iterable[object] = (),
              exchange: Iterable[object] = (),
              program: Optional[str] = None):
        """Watch one dispatched program's in-flight window.

        ``ref``      — output the program will materialize
        ``t0``       — perf_counter at dispatch
        ``spans``    — legacy ``(name, cat)`` spans closed over the
                       window (the old pipewatch/z3watch emissions)
        ``timers``   — ``(PhaseTimer, key)`` sinks fed the window
        ``compute``/``exchange`` — OverlapTracker sides fed the window
        ``program``  — ledger key: which program's collectives this
                       window covers (None = no comm emission)
        """
        ops = tuple(get_ledger().ops_of(program)) if program else ()
        with self._lock:
            self._seq += 1
            seq = self._seq
        if ops:
            # note the start BEFORE blocking, on the caller's thread:
            # a crash mid-window must find this sample in the ring
            from dgl_operator_tpu.obs.flight import get_flight
            get_flight().note("comm", phase="start", seq=seq,
                              op=ops[0].op, axis=ops[0].axis,
                              program=ops[0].program, step=step)
        return self._pool.submit(self._observe, ref, t0, step,
                                 tuple(spans), tuple(timers),
                                 tuple(compute), tuple(exchange),
                                 ops, seq)

    def drain(self) -> None:
        """Barrier on the FIFO: every submitted window is closed."""
        self._pool.submit(lambda: None).result()

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True, cancel_futures=True)

    # -- the observe body (watch thread) ------------------------------
    def _observe(self, ref, t0, step, spans, timers, compute,
                 exchange, ops, seq) -> None:
        import jax

        slot_times = self._slot_ready_times(ref, ops)
        try:
            jax.block_until_ready(ref)
        except RuntimeError:
            # the consuming program already donated this buffer away —
            # deletion proves the dispatch completed, so close the
            # window at "now" instead of dropping the sample
            pass
        t1 = time.perf_counter()
        dt = max(t1 - t0, 0.0)
        for timer, key in timers:
            timer.add(key, dt)
        for tracker in compute:
            tracker.add_compute(t0, t1)
        for tracker in exchange:
            tracker.add_exchange(t0, t1)
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        for name, cat in spans:
            obs.tracer.complete(name, t0, t1, cat=cat, step=step)
        if ops:
            self._emit_comm(obs, ops, t0, t1, step, slot_times)
            from dgl_operator_tpu.obs.flight import get_flight
            get_flight().note("comm", phase="done", seq=seq,
                              op=ops[0].op, step=step)

    @staticmethod
    def _slot_ready_times(ref, ops) -> Tuple[float, ...]:
        """Per-shard readiness stamps (first sharded leaf, in slot
        order) — the raw material for collective-granularity straggler
        skew. Best-effort: committed single-device arrays and donated
        buffers just yield no skew sample."""
        if not ops:
            return ()
        try:
            import jax

            for leaf in jax.tree_util.tree_leaves(ref):
                shards = getattr(leaf, "addressable_shards", None)
                if not shards or len(shards) < 2:
                    continue
                out = []
                for shard in shards:
                    jax.block_until_ready(shard.data)
                    out.append(time.perf_counter())
                return tuple(out)
        except Exception:  # noqa: BLE001 — skew is opportunistic
            return ()
        return ()

    def _emit_comm(self, obs, ops, t0, t1, step, slot_times) -> None:
        """Per-collective emission for one closed window: spans,
        byte/second counters, achieved-vs-peak gauges, slot skew."""
        dt = max(t1 - t0, 1e-9)
        peaks = self._link_peaks()
        m = obs.metrics
        bytes_c = m.counter(
            "comm_bytes_total",
            "analytic bytes moved per collective op",
            labels=("op", "axis"))
        secs_c = m.counter(
            "comm_seconds",
            "in-flight wall-clock attributed per collective op "
            "(window split by byte share when ops co-reside)",
            labels=("op", "axis"))
        bw_g = m.gauge(
            "comm_link_gbps",
            "achieved link bandwidth of the latest window per "
            "collective op (analytic bytes over the measured window "
            "— a lower bound when ops share the window)",
            labels=("op", "axis"))
        util_g = m.gauge(
            "comm_link_util",
            "achieved fraction of the resolved ICI/DCN link peak per "
            "collective op",
            labels=("op", "axis", "link"))
        total = float(sum(o.bytes_per_call for o in ops)) or 1.0
        for o in ops:
            share = dt * (o.bytes_per_call / total)
            gbps = o.bytes_per_call / dt / 1e9
            link = link_of(o.axis)
            peak = float(peaks.get(f"peak_{link}_gbps") or 0.0)
            obs.tracer.complete(
                o.op, t0, t1, cat="comm", axis=o.axis,
                bytes=o.bytes_per_call, program=o.program,
                fused_depth=o.fused_depth, step=step)
            bytes_c.inc(o.bytes_per_call, op=o.op, axis=o.axis)
            secs_c.inc(round(share, 6), op=o.op, axis=o.axis)
            bw_g.set(round(gbps, 6), op=o.op, axis=o.axis)
            if peak > 0:
                util_g.set(round(gbps / peak, 6), op=o.op,
                           axis=o.axis, link=link)
            _account_axis(o.axis, o.bytes_per_call)
        if slot_times:
            # attribute slot skew to the window's dominant collective
            # (ops_of sorts largest-first)
            top = ops[0]
            skew_c = m.counter(
                "comm_slot_seconds",
                "cumulative per-mesh-slot readiness lag of the "
                "dominant collective — the straggler-skew series "
                "(slot i ready at t_i, lag = t_i - dispatch)",
                labels=("op", "axis", "slot"))
            for i, ts in enumerate(slot_times):
                skew_c.inc(round(max(ts - t0, 0.0), 6), op=top.op,
                           axis=top.axis, slot=str(i))


# ------------------------------------------------------------------
# bench summary (pinned keys)
# ------------------------------------------------------------------
def comm_summary(obs_dir: str) -> Optional[Dict[str, object]]:
    """Comm-plane summary of a finished run's obs dir, shaped by the
    pinned ``benchkeys.COMM_KEYS`` (benchmarks/bench_comm.py tracks it
    as COMM.json; the doctor comm block renders it). None when the run
    emitted no comm metrics at all."""
    from dgl_operator_tpu.obs.prof import _gauge_value, _merged_metrics

    merged = _merged_metrics(obs_dir)

    def _totals(name: str) -> Dict[Tuple[str, str], float]:
        fam = merged.get(name) or {}
        out: Dict[Tuple[str, str], float] = {}
        for s in fam.get("samples", []):
            lb = s.get("labels", {})
            key = (str(lb.get("op", "?")), str(lb.get("axis", "?")))
            out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
        return out

    byte_tot = _totals("comm_bytes_total")
    if not byte_tot:
        return None
    sec_tot = _totals("comm_seconds")
    per_op: Dict[str, Dict[str, float]] = {}
    for (op, axis), nbytes in sorted(byte_tot.items()):
        secs = sec_tot.get((op, axis), 0.0)
        per_op[f"{op}@{axis}"] = {
            "bytes": round(nbytes, 1),
            "seconds": round(secs, 6),
            "gbps": round(nbytes / max(secs, 1e-9) / 1e9, 6)
            if secs > 0 else 0.0,
        }
    top_key = max(per_op, key=lambda k: per_op[k]["bytes"])
    out: Dict[str, object] = {
        "comm_ops": sorted({op for op, _ in byte_tot}),
        "comm_bytes_total": round(sum(byte_tot.values()), 1),
        "comm_seconds": round(sum(sec_tot.values()), 6),
        "top_op": top_key,
        "top_op_gbps": per_op[top_key]["gbps"],
        "axis_util_max": _gauge_value(merged, "comm_link_util"),
        "overlap_ratio": _gauge_value(merged, "train_overlap_ratio"),
    }
    assert tuple(out) == COMM_KEYS
    out["per_op"] = per_op
    return out
