"""Structured event log — JSONL sink plus a console sink.

Every record carries the run id, host, pid, role, and a wall-clock
timestamp, followed by the event's own key/value payload. The console
sink preserves the exact human-readable lines the reference-shaped
drivers have always printed (log scrapers keep working), while the
JSONL sink makes the same moments machine-readable after the process
is gone.

Append semantics: records are written with one ``open(..., "a")`` per
emit — O_APPEND keeps concurrent writers (driver + trainer
subprocesses sharing one ``events.jsonl``) line-atomic for the short
records emitted here, and no file handle outlives the call, so a
deleted run directory degrades the sink instead of wedging later
emitters.

Stdlib-only — imported by the control-plane image.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

EVENTS_JSONL = "events.jsonl"


class EventLog:
    def __init__(self, path: Optional[str] = None, console: bool = True,
                 base: Optional[Dict[str, object]] = None):
        self.path = path
        self.console = console
        self.base = dict(base or {})
        self._warned = False

    def emit(self, event: str, message: Optional[str] = None,
             **fields) -> Dict[str, object]:
        """Record one structured event (JSONL sink only)."""
        rec: Dict[str, object] = {"ts": round(time.time(), 6)}
        rec.update(self.base)
        rec["event"] = event
        rec.update(fields)
        if message is not None:
            rec["message"] = message
        self._append(rec)
        return rec

    def log(self, message: str, event: str = "log",
            **fields) -> Dict[str, object]:
        """Console sink + event record: prints exactly ``message``
        (with ``flush=True``) and captures it as an event — the
        replacement for the drivers' bare ``print()`` calls."""
        if self.console:
            print(message, flush=True)
        return self.emit(event, message=message, **fields)

    def console_line(self, message: str) -> None:
        """Console-only decorative output (separators); not an event."""
        if self.console:
            print(message, flush=True)

    def _append(self, rec: Dict[str, object]) -> None:
        if not self.path:
            return
        try:
            line = json.dumps(rec, default=str)
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except (OSError, TypeError, ValueError) as exc:
            # telemetry must never fail the job: drop the file sink
            # (loudly, once) and keep the console alive
            if not self._warned:
                self._warned = True
                print(f"obs: event write to {self.path} failed ({exc});"
                      " falling back to console only", flush=True)
            self.path = None
