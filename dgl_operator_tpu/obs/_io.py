"""Shared file plumbing for the telemetry artifacts.

The obs artifacts are updated by SEVERAL processes of one run (the
tpurun driver plus every trainer subprocess it launches share one
``obs/`` directory), so the two rules here are: every publish is
atomic (tmp + rename — a reader never sees a torn file), and every
read-merge-write update runs under an advisory cross-process lock so
concurrent flushes from two trainers can't lose each other's update.

Stdlib-only: this package is imported by the control-plane image,
which ships neither numpy nor jax.
"""

from __future__ import annotations

import contextlib
import json
import os

LOCK_NAME = ".obs.lock"


def atomic_write(path: str, data: str) -> None:
    """Publish ``data`` at ``path`` atomically (tmp + rename); the pid
    suffix keeps concurrent writers' tmp files from colliding."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


@contextlib.contextmanager
def dir_lock(directory: str):
    """Advisory exclusive lock on ``directory``'s obs artifacts,
    serializing read-merge-write updates across the run's processes.
    Degrades to a no-op where flock is unavailable."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover — non-POSIX fallback
        yield
        return
    with open(os.path.join(directory, LOCK_NAME), "a") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def read_json(path: str, default):
    """Best-effort JSON read: a missing or torn file yields ``default``
    (telemetry merges must survive a crashed previous writer)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default
