"""Shared file plumbing for the telemetry artifacts.

The obs artifacts are updated by SEVERAL processes of one run (the
tpurun driver plus every trainer subprocess it launches share one
``obs/`` directory), so the two rules here are: every publish is
atomic (tmp + rename — a reader never sees a torn file), and every
read-merge-write update runs under an advisory cross-process lock so
concurrent flushes from two trainers can't lose each other's update.

The lock is two-layered:

- an ``flock`` on ``.obs.lock`` serializes same-host flushers and is
  released by the kernel when the holder dies — it can never wedge;
- a pid-stamped lock DIRECTORY (``.obs.lock.d``) makes the holder
  visible across hosts sharing the obs volume (flock is unreliable on
  network filesystems). A holder killed mid-flush — exactly what the
  chaos plan's ``train:kill:<step>`` SIGTERM can do — orphans the
  directory; later flushers detect the stale lock (dead owner pid on
  this host, or over-age) and BREAK it instead of wedging forever,
  counting ``obs_lock_broken_total``.

Stdlib-only: this package is imported by the control-plane image,
which ships neither numpy nor jax.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import socket
import time
from typing import Optional

LOCK_NAME = ".obs.lock"
LOCK_DIR_NAME = ".obs.lock.d"
OWNER_NAME = "owner"
# a cross-host holder silent this long is presumed dead (flushes are
# sub-second; this bounds how long a lost remote host can block)
STALE_LOCK_S = 30.0
_POLL_S = 0.005


def atomic_write(path: str, data: str) -> None:
    """Publish ``data`` at ``path`` atomically (tmp + rename); the pid
    suffix keeps concurrent writers' tmp files from colliding."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        pass   # exists but not ours — alive
    return True


def lock_stale_reason(lock_dir: str,
                      host: Optional[str] = None,
                      stale_s: float = STALE_LOCK_S) -> Optional[str]:
    """Why ``lock_dir`` is safe to break, or None while its holder may
    still be alive: ``dead-pid`` when the stamped owner pid is gone on
    this host, ``over-age`` when the stamp (or, with no owner file yet,
    the directory itself) is older than ``stale_s``."""
    host = host or socket.gethostname()
    owner = read_json(os.path.join(lock_dir, OWNER_NAME), {})
    pid = owner.get("pid")
    if owner.get("host") == host and isinstance(pid, int):
        if not _pid_alive(pid):
            return "dead-pid"
    ts = owner.get("ts")
    if not isinstance(ts, (int, float)):
        try:   # killed between mkdir and the owner stamp
            ts = os.stat(lock_dir).st_mtime
        except OSError:
            return None   # raced with the holder's own release
    if time.time() - ts > stale_s:
        return "over-age"
    return None


def _count_broken(reason: str, lock_dir: str) -> None:
    """Best-effort telemetry for a broken lock (lazy import — this
    module sits beneath the obs package)."""
    try:
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        obs.metrics.counter(
            "obs_lock_broken_total",
            "stale obs flush locks broken (orphaned by a killed "
            "flusher)", labels=("reason",)).inc(reason=reason)
        obs.events.emit("obs_lock_broken", reason=reason, path=lock_dir)
    except Exception:   # noqa: BLE001 — telemetry never fails the job
        pass


def break_stale_lock(lock_dir: str, host: Optional[str] = None,
                     stale_s: float = STALE_LOCK_S) -> Optional[str]:
    """Break ``lock_dir`` iff it is provably stale; returns the reason
    or None (lock still live)."""
    reason = lock_stale_reason(lock_dir, host=host, stale_s=stale_s)
    if reason is None:
        return None
    shutil.rmtree(lock_dir, ignore_errors=True)
    _count_broken(reason, lock_dir)
    return reason


@contextlib.contextmanager
def dir_lock(directory: str, timeout: float = STALE_LOCK_S):
    """Advisory exclusive lock on ``directory``'s obs artifacts,
    serializing read-merge-write updates across the run's processes.
    flock degrades to a no-op where unavailable; the lock directory
    degrades (loudly never — silently) when the obs directory itself
    vanished mid-run. Stale lock directories are broken, not waited
    on; a live foreign lock still held past ``timeout`` is treated as
    stale too (wedging every later flush is the worse failure)."""
    flock_f = None
    try:
        import fcntl
        flock_f = open(os.path.join(directory, LOCK_NAME), "a")
        fcntl.flock(flock_f, fcntl.LOCK_EX)
    except ImportError:   # pragma: no cover — non-POSIX fallback
        fcntl = None
    except OSError:       # obs dir deleted under us
        flock_f = None
        fcntl = None
    lock_dir = os.path.join(directory, LOCK_DIR_NAME)
    held = False
    deadline = time.monotonic() + timeout
    while True:
        try:
            os.mkdir(lock_dir)
            held = True
            break
        except FileExistsError:
            if break_stale_lock(lock_dir) is not None:
                continue
            if time.monotonic() > deadline:
                shutil.rmtree(lock_dir, ignore_errors=True)
                _count_broken("timeout", lock_dir)
                continue
            time.sleep(_POLL_S)
        except OSError:   # obs dir deleted — flock alone must do
            break
    if held:
        try:
            with open(os.path.join(lock_dir, OWNER_NAME), "w") as f:
                json.dump({"pid": os.getpid(),
                           "host": socket.gethostname(),
                           "ts": time.time()}, f)
        except OSError:
            pass
    try:
        yield
    finally:
        if held:
            shutil.rmtree(lock_dir, ignore_errors=True)
        if flock_f is not None:
            try:
                fcntl.flock(flock_f, fcntl.LOCK_UN)
            except OSError:
                pass
            flock_f.close()


def read_json(path: str, default):
    """Best-effort JSON read: a missing or torn file yields ``default``
    (telemetry merges must survive a crashed previous writer)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default
