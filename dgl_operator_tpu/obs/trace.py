"""Trace spans — a nestable, ``perf_counter``-based span API exported
as Chrome trace-event JSON (``trace.json``), loadable in Perfetto /
``chrome://tracing``.

Spans record complete ("X") events: epoch-anchored microsecond
timestamps plus duration, keyed by (pid, tid) so nesting falls out of
containment on the same thread track and the driver + each trainer
subprocess appear as separate process tracks in one merged file.

Stdlib-only — imported by the control-plane image.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from dgl_operator_tpu.obs._io import atomic_write, dir_lock, read_json

TRACE_JSON = "trace.json"


class Tracer:
    def __init__(self, process_name: Optional[str] = None,
                 pid: Optional[int] = None):
        self.pid = os.getpid() if pid is None else pid
        self.process_name = process_name
        self._lock = threading.Lock()
        self._events: List[Dict[str, object]] = []
        # maps perf_counter() readings onto the wall clock so every
        # process's spans land on one shared timeline in the merged file
        self._epoch0 = time.time() - time.perf_counter()

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "", **args):
        """Time a block as one complete trace event; nest freely."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.complete(name, t0, time.perf_counter(), cat=cat, **args)

    @staticmethod
    def _stamp_trace(args: Dict[str, object]) -> Dict[str, object]:
        """Fold the active trace context (obs/tracectx.py) into a
        span's args, so every span recorded while a request/step
        context is live joins its trace tree: the active span becomes
        this record's PARENT (plain spans carry no id of their own).
        Spans with explicit ids (tracectx.span's records) pass
        through untouched."""
        if "trace_id" in args:
            return args
        from dgl_operator_tpu.obs.tracectx import current
        ctx = current()
        if ctx is not None:
            args = dict({"trace_id": ctx.trace_id,
                         "parent_id": ctx.span_id}, **args)
        return args

    def complete(self, name: str, t0: float, t1: float, cat: str = "",
                 **args) -> None:
        """Record a span from explicit ``perf_counter()`` endpoints —
        for call sites that already hold their own timestamps."""
        args = self._stamp_trace(args)
        ev: Dict[str, object] = {
            "name": name, "cat": cat or "obs", "ph": "X",
            "ts": round((self._epoch0 + t0) * 1e6, 1),
            "dur": max(round((t1 - t0) * 1e6, 1), 0.0),
            "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def counter(self, name: str, series: Dict[str, float],
                cat: str = "prof") -> None:
        """Chrome counter-track sample (``ph="C"``): Perfetto renders
        each named counter as a stacked value track under the process'
        span rows — how the profiler (obs/prof.py) shows MFU and the
        HBM watermark directly beneath the step spans. ``series`` maps
        series label -> value; samples on the same name accumulate
        into one track."""
        ev: Dict[str, object] = {
            "name": name, "cat": cat or "prof", "ph": "C",
            "ts": round(time.time() * 1e6, 1),
            "pid": self.pid, "tid": 0,
            "args": {k: float(v) for k, v in series.items()}}
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, cat: str = "", **args) -> None:
        """Zero-duration marker (faults, kills) on this thread's track."""
        args = self._stamp_trace(args)
        ev: Dict[str, object] = {
            "name": name, "cat": cat or "obs", "ph": "i", "s": "t",
            "ts": round(time.time() * 1e6, 1),
            "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def chrome(self) -> Dict[str, object]:
        """This process's events in Chrome trace-event JSON object form
        (a process_name metadata record labels the track)."""
        evs: List[Dict[str, object]] = []
        if self.process_name:
            evs.append({"name": "process_name", "ph": "M",
                        "pid": self.pid, "tid": 0,
                        "args": {"name": self.process_name}})
        with self._lock:
            evs.extend(self._events)
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


def write_chrome(directory: str, tracer: Tracer) -> None:
    """Publish this process's spans into the run's shared
    ``trace.json``: other processes' events are kept, this pid's are
    replaced (re-flushing is idempotent). Runs under the obs directory
    lock; the write is atomic."""
    path = os.path.join(directory, TRACE_JSON)
    own = tracer.chrome()
    with dir_lock(directory):
        old = read_json(path, {})
        others = [e for e in old.get("traceEvents", [])
                  if isinstance(e, dict) and e.get("pid") != tracer.pid]
        atomic_write(path, json.dumps(
            {"traceEvents": others + own["traceEvents"],
             "displayTimeUnit": "ms"}, indent=1))
