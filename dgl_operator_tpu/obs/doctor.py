"""``tpu-doctor`` — render a job's telemetry into a diagnosis a human
(or the controller) can act on.

The reference stack answers "why is this job slow/stuck" with
``kubectl exec`` and hope. Here the answer is computed from artifacts
the run already left behind: the doctor loads the ``obs/job/`` view
(building one in place from a plain single-host ``obs/`` directory
when no collection ran), runs the analytics (``obs/analyze.py``), and
emits both a human-readable report and ``obs/job/report.json``.

Usage::

    tpu-doctor [<obs-dir>]                 # console entry point
    python -m dgl_operator_tpu.obs.doctor [<obs-dir>] [--json]

The obs directory defaults to ``$TPU_OPERATOR_OBS_DIR``, then
``<workspace>/obs``. Exit status: 0 healthy-ish (info/warning only),
1 when any finding is critical, 2 usage errors — so CI and runbooks
can gate on it (docs/operations.md: "job is slow/stuck → run
tpu-doctor").

Stdlib-only — runs in the control-plane image.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from dgl_operator_tpu.obs import OBS_DIR_ENV
from dgl_operator_tpu.obs._io import atomic_write
from dgl_operator_tpu.obs.analyze import (DEFAULT_STALL_FACTOR,
                                          DEFAULT_STRAGGLER_RATIO,
                                          analyze_job, load_events)
from dgl_operator_tpu.obs.collect import (EVENTS_JSONL, METRICS_JSON,
                                          job_dir_of, merge_job_view)
from dgl_operator_tpu.obs.metrics import quantile_from_counts

REPORT_JSON = "report.json"
_SEV_MARK = {"critical": "[CRITICAL]", "warning": "[WARNING ]",
             "info": "[info    ]"}


def resolve_obs_dir(obs_dir: Optional[str],
                    workspace: Optional[str]) -> str:
    d = (obs_dir or os.environ.get(OBS_DIR_ENV)
         or (os.path.join(workspace, "obs") if workspace else None))
    if not d:
        raise SystemExit(2)
    return os.path.abspath(d)


def build_report(obs_dir: str,
                 straggler_ratio: float = DEFAULT_STRAGGLER_RATIO,
                 stall_factor: float = DEFAULT_STALL_FACTOR) -> Dict:
    """Ensure a job view exists (a plain obs dir becomes its own
    single-source view), analyze it, and persist ``job/report.json``."""
    job_dir = job_dir_of(obs_dir)
    if not os.path.exists(os.path.join(job_dir, EVENTS_JSONL)):
        merge_job_view(job_dir, sources=[("local", obs_dir)])
    report = analyze_job(obs_dir, straggler_ratio=straggler_ratio,
                         stall_factor=stall_factor)
    report["obs_dir"] = obs_dir
    slo = serve_slo(os.path.join(job_dir, METRICS_JSON))
    if slo:
        report["serve_slo"] = slo
    fleet = serve_fleet(os.path.join(job_dir, METRICS_JSON),
                        os.path.join(job_dir, EVENTS_JSONL))
    if fleet:
        report["serve_fleet"] = fleet
    ss = state_sharding(os.path.join(job_dir, METRICS_JSON))
    if ss:
        report["state_sharding"] = ss
    dp = dataplane(os.path.join(job_dir, METRICS_JSON))
    if dp:
        report["dataplane"] = dp
    tn = tuning(os.path.join(job_dir, METRICS_JSON))
    if tn:
        report["tuning"] = tn
    cm = comm(obs_dir)
    if cm:
        report["comm"] = cm
    fl = flight_incidents(obs_dir)
    if fl:
        report["flight"] = fl
    try:
        atomic_write(os.path.join(job_dir, REPORT_JSON),
                     json.dumps(report, indent=2, sort_keys=True))
        report["report_path"] = os.path.join(job_dir, REPORT_JSON)
    except OSError:
        report["report_path"] = None   # read-only view still renders
    return report


def serve_slo(metrics_json_path: str) -> Optional[Dict]:
    """Serving-plane SLO block from a finished run's merged metrics
    snapshot: request-latency quantiles (bucket-interpolated —
    ``obs.metrics.quantile_from_counts``, the estimator
    ``bench_serve`` cross-checks against exact samples), request/batch
    counts and padding occupancy. ``None`` when the run had no serving
    plane — training-only reports are unchanged."""
    try:
        with open(metrics_json_path) as f:
            merged = json.load(f).get("merged", {})
    except (OSError, ValueError):
        return None
    fam = merged.get("serve_request_seconds")
    if not fam or not fam.get("samples"):
        return None
    buckets = fam.get("buckets", [])
    counts = [0] * (len(buckets) + 1)
    for s in fam["samples"]:
        for i, c in enumerate(s.get("counts", [])):
            counts[i] += c
    out: Dict = {"requests": sum(counts)}
    for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
        v = quantile_from_counts(buckets, counts, q)
        out[key] = round(v * 1e3, 3) if v is not None else None

    def _counter(name):
        f = merged.get(name, {})
        return sum(s.get("value", 0) for s in f.get("samples", []))

    out["batches"] = int(_counter("serve_batches_total"))
    occ = merged.get("serve_batch_occupancy", {})
    tot = sum(s.get("count", 0) for s in occ.get("samples", []))
    ssum = sum(s.get("sum", 0.0) for s in occ.get("samples", []))
    out["mean_batch_occupancy"] = (round(ssum / tot, 4) if tot else None)
    out["errors"] = int(_counter("serve_errors_total"))
    out["shed"] = int(_counter("serve_requests_shed_total"))
    out["slo_breaches"] = int(_counter("slo_breaches_total"))
    return out


def serve_fleet(metrics_json_path: str,
                events_path: Optional[str] = None) -> Optional[Dict]:
    """Serve-fleet block from a finished run's merged metrics (+ the
    job event ledger): replica fan-out counts, failover/drain/regrow
    tallies, and the canary-promotion history (``serve/router.py``,
    docs/serving.md). ``None`` when no router ran — single-replica and
    training-only reports are unchanged."""
    try:
        with open(metrics_json_path) as f:
            merged = json.load(f).get("merged", {})
    except (OSError, ValueError):
        return None
    fam = merged.get("fleet_requests_total")
    if not fam or not fam.get("samples"):
        return None

    def _counter(name, label=None):
        f = merged.get(name, {})
        return sum(s.get("value", 0) for s in f.get("samples", [])
                   if label is None or s.get("labels", {}) == label)

    out: Dict = {
        "per_replica": {
            s.get("labels", {}).get("replica", "?"):
            int(s.get("value", 0))
            for s in fam["samples"]},
        "retries": int(_counter("fleet_retries_total")),
        "failovers": int(_counter("fleet_failovers_total")),
        "shed": int(_counter("fleet_shed_total")),
        "canary_mirrors": int(_counter("fleet_canary_mirrors_total")),
        "promoted": int(_counter("ckpt_promotions_total",
                                 {"result": "promoted"})),
        "rolled_back": int(_counter("ckpt_promotions_total",
                                    {"result": "rolled_back"})),
    }
    up = merged.get("fleet_replicas_up", {})
    vals = [s.get("value") for s in up.get("samples", [])
            if s.get("value") is not None]
    out["replicas_up"] = int(max(vals)) if vals else None
    # drain/regrow + canary verdict story from the event ledger
    if events_path:
        downs, regrows, verdicts = 0, 0, []
        for e in load_events(events_path):
            ev = e.get("event")
            if ev == "fleet_replica_down":
                downs += 1
            elif ev == "fleet_replica_regrow":
                regrows += 1
            elif ev == "fleet_canary_verdict":
                verdicts.append({
                    "verdict": e.get("verdict"),
                    "replica": e.get("replica"),
                    "divergence": e.get("divergence"),
                    "nonfinite": e.get("nonfinite")})
        out["replica_downs"] = downs
        out["replica_regrows"] = regrows
        out["canary_verdicts"] = verdicts
    return out


def state_sharding(metrics_json_path: str) -> Optional[Dict]:
    """State-sharding block from a finished run's merged metrics
    snapshot: per-role (dist trainer / kge trainer) replicated-vs-
    sharded per-slot MiB for params and optimizer state, plus the
    savings ratio — the gauges the trainers emit through
    ``parallel.shardrules.emit_state_gauges``. ``None`` when no
    trainer ran (launch-only obs dirs are unchanged)."""
    try:
        with open(metrics_json_path) as f:
            merged = json.load(f).get("merged", {})
    except (OSError, ValueError):
        return None
    fam = merged.get("train_state_mib_per_slot")
    if not fam or not fam.get("samples"):
        return None
    roles: Dict[str, Dict] = {}
    for s in fam["samples"]:
        lb = s.get("labels", {})
        roles.setdefault(lb.get("role", "?"), {}).setdefault(
            lb.get("kind", "?"), {})[lb.get("mode", "?")] = s["value"]
    ratios = {}
    for s in merged.get("train_state_savings_ratio",
                        {}).get("samples", []):
        ratios[s.get("labels", {}).get("role", "?")] = s["value"]
    return {"roles": roles, "savings_ratio": ratios}


def dataplane(metrics_json_path: str) -> Optional[Dict]:
    """Feature data-plane block from the merged metrics snapshot
    (docs/dataplane.md): per-role feature-store MiB/slot in the active
    storage dtype, the storage-dtype backing bytes, and cold-tier rows
    demand-paged since load — the gauges the trainers and the serve
    engine emit through ``graph.featstore.emit_dataplane_gauges``.
    ``None`` when no feature plane reported (launch-only obs dirs are
    unchanged)."""
    try:
        with open(metrics_json_path) as f:
            merged = json.load(f).get("merged", {})
    except (OSError, ValueError):
        return None
    fam = merged.get("data_feat_mib_per_slot")
    if not fam or not fam.get("samples"):
        return None
    roles: Dict[str, Dict] = {}
    for s in fam["samples"]:
        lb = s.get("labels", {})
        roles.setdefault(lb.get("role", "?"), {}).update(
            dtype=lb.get("dtype", "?"), mib_per_slot=s["value"])
    for s in merged.get("data_feat_backing_mib",
                        {}).get("samples", []):
        role = s.get("labels", {}).get("role", "?")
        roles.setdefault(role, {})["backing_mib"] = s["value"]
    for s in merged.get("data_feat_paged_rows",
                        {}).get("samples", []):
        role = s.get("labels", {}).get("role", "?")
        roles.setdefault(role, {})["paged_rows"] = int(s["value"])
    return {"roles": roles}


def tuning(metrics_json_path: str) -> Optional[Dict]:
    """Auto-tuning block from the merged metrics snapshot (ISSUE 9):
    which tuned-manifest knob overrides the trainers actually applied
    (``autotune_overrides_applied_total`` from
    ``autotune.knobs.apply_tuned``), how many search probes ran /
    ledger-skipped, the winning probe score, and whether a skew-aware
    placement rewrote the working hostfile. ``None`` when the run
    never touched the autotune plane — untuned reports are
    unchanged."""
    try:
        with open(metrics_json_path) as f:
            merged = json.load(f).get("merged", {})
    except (OSError, ValueError):
        return None
    knobs = []
    for s in merged.get("autotune_overrides_applied_total",
                        {}).get("samples", []):
        k = s.get("labels", {}).get("knob")
        if k:
            knobs.append(k)
    probes = {s.get("labels", {}).get("status", "?"):
              int(s.get("value", 0))
              for s in merged.get("autotune_probes_total",
                                  {}).get("samples", [])}

    def _first_value(name):
        samples = merged.get(name, {}).get("samples", [])
        return samples[0].get("value") if samples else None

    manifests = _first_value("autotune_manifest_loaded_total")
    placements = _first_value("autotune_placements_total")
    best = _first_value("autotune_best_score")
    if not (knobs or probes or manifests or placements
            or best is not None):
        return None
    return {"overrides_applied": sorted(knobs),
            "probes": probes,
            "best_score": best,
            "manifests_loaded": int(manifests or 0),
            "placements_applied": int(placements or 0)}


def comm(obs_dir: str) -> Optional[Dict]:
    """Communication-plane block (ISSUE 19): the pinned
    ``benchkeys.COMM_KEYS`` summary from the per-collective ledger
    metrics (``obs.comm.comm_summary``) — per-op achieved bytes /
    seconds / GB/s, the peak link-utilization gauge, and the run's
    exchange/compute overlap. ``None`` when the run emitted no comm
    metrics — pre-comm-plane obs dirs are unchanged."""
    from dgl_operator_tpu.obs.comm import comm_summary
    try:
        return comm_summary(obs_dir)
    except (OSError, ValueError):
        return None


def flight_incidents(obs_dir: str) -> Optional[List[Dict]]:
    """Incident timeline from crash-safe flight-recorder dumps
    (``obs/flight.py``: ``flight-<pid>.json``, written on fault /
    SIGTERM / chaos kill): who dumped, why, and — the question an
    incident review always starts with — which collective was in
    flight when the process died. ``None`` when no process dumped."""
    from dgl_operator_tpu.obs.flight import load_flights
    dumps = load_flights(obs_dir)
    if not dumps:
        return None
    out: List[Dict] = []
    for d in dumps:
        samples = d.get("samples") or []
        out.append({
            "host": d.get("host"), "pid": d.get("pid"),
            "role": d.get("role"), "reason": d.get("reason"),
            "ts": d.get("ts"), "inflight": d.get("inflight"),
            "last_comm": d.get("last_comm"),
            "samples": len(samples),
            "last_kinds": [s.get("kind") for s in samples[-5:]],
        })
    return out


def render(report: Dict) -> str:
    """The human-readable diagnosis."""
    s = report.get("summary", {})
    lines: List[str] = []
    lines.append("tpu-doctor" + (f" — run {report['run']}"
                                 if report.get("run") else ""))
    lines.append(f"  obs dir : {report.get('obs_dir', '?')}")
    lines.append(f"  events  : {s.get('events', 0)}  "
                 f"workers: {len(s.get('workers', []))}  "
                 f"epochs: {s.get('epochs', 0)}  "
                 f"last step: {s.get('last_step')}")
    if s.get("phases"):
        parts = ", ".join(
            f"{p.get('phase')}:{p.get('title') or '?'} "
            f"{p.get('seconds', 0):.1f}s" for p in s["phases"])
        lines.append(f"  phases  : {parts}")
    if s.get("phases_skipped"):
        lines.append("  skipped : " + ", ".join(
            str(p.get("phase")) for p in s["phases_skipped"])
            + " (ledger resume)")
    if s.get("faults_injected"):
        lines.append(f"  faults  : {len(s['faults_injected'])} injected "
                     "(chaos plan)")
    lines.append(f"  retries : {s.get('retries', 0)}"
                 + (f"  exhausted: {s['retry_exhausted']}"
                    if s.get("retry_exhausted") else ""))
    for r in s.get("resume_points", []):
        lines.append(f"  resume  : step {r.get('step')} "
                     f"by {r.get('worker')}")
    if s.get("lock_breaks"):
        lines.append(f"  locks   : {s['lock_breaks']} stale obs lock(s) "
                     "broken")
    skew = report.get("skew") or {}
    if skew:
        lines.append("  skew (slowest vs median per bucket):")
        for bucket, v in sorted(skew.items()):
            ratio = v.get("ratio")
            lines.append(
                f"    {bucket:<10} median {v['median_s']:.3f}s  "
                f"slowest {v['slowest_s']:.3f}s"
                + (f"  ({ratio}x, {v['slowest']})"
                   if ratio is not None else ""))
    pipe = report.get("pipeline")
    if pipe:
        # the starved-vs-saturated line (ISSUE 7): is the device
        # waiting on the input plane, or is the pipeline keeping ahead?
        lines.append(
            f"  pipeline: {pipe['verdict']} — stall "
            f"{pipe['stall_s']:.3f}s vs dispatch "
            f"{pipe['dispatch_s']:.3f}s"
            + (f", exchange {pipe['exchange_s']:.3f}s hidden off-thread"
               if pipe.get("exchange_s") else "")
            + ("  (sampler-starved: raise num_samplers/prefetch)"
               if pipe["verdict"] == "starved" else ""))
    hw = report.get("hardware")
    if hw:
        # how far from the hardware ceiling the run actually ran
        # (obs/prof.py): MFU, the binding roofline resource, the HBM
        # watermark vs the analytic budget, and the compile bill
        parts = []
        if hw.get("mfu") is not None:
            line = f"MFU {hw['mfu']:.4f}"
            if hw.get("roofline_bound"):
                frac = hw["roofline_fracs"].get(hw["roofline_bound"])
                line += (f" ({hw['roofline_bound']}-bound"
                         + (f" at {frac:.4f} of peak" if frac is not None
                            else "") + ")")
            parts.append(line)
        if hw.get("hbm_watermark_mib") is not None:
            line = f"HBM {hw['hbm_watermark_mib']:.1f} MiB watermark"
            if hw.get("hbm_predicted_mib") is not None:
                line += f" vs {hw['hbm_predicted_mib']:.1f} predicted"
            parts.append(line)
        if hw.get("jit_compiles"):
            parts.append(f"{hw['jit_compiles']} XLA compile(s), "
                         f"{hw['jit_compile_seconds']:.1f}s")
        if parts:
            lines.append("  hardware: " + "; ".join(parts))
    el = report.get("elasticity")
    if el:
        # the elastic fault-domain story (docs/elasticity.md): who
        # died, how the mapping reshaped, and whether the checkpoint
        # hardening (fencing, checksum fallback) had to act
        parts = []
        if el.get("dead_hosts"):
            parts.append("dead: " + ", ".join(el["dead_hosts"]))
        if el.get("shrinks"):
            w = (f" (width {el['full_width']}→{el['width']})"
                 if el.get("width") is not None else "")
            parts.append(f"{el['shrinks']} shrink(s){w}")
        if el.get("regrows"):
            parts.append(f"{el['regrows']} regrow(s)")
        if el.get("last_epoch") is not None:
            parts.append(f"epoch {el['last_epoch']}")
        if el.get("fence_rejections"):
            parts.append(f"{el['fence_rejections']} zombie "
                         "publication(s) fenced")
        if el.get("ckpt_fallbacks"):
            parts.append(f"{el['ckpt_fallbacks']} ckpt fallback(s) "
                         "to last-known-good")
        lines.append("  elastic : " + ("; ".join(parts) or "active"))
    mh = report.get("model_health")
    if mh:
        # the model-health story (obs/quality.py): did the trajectory
        # itself go bad, and did the automated response handle it?
        parts = []
        if mh.get("faults"):
            descs = []
            for f in mh["faults"]:
                d = f"step {f.get('step')}"
                if f.get("partition") is not None:
                    d += f" part {f.get('partition')}"
                descs.append(d)
            parts.append(f"{len(mh['faults'])} numerics fault(s) "
                         f"({', '.join(descs)})")
        if mh.get("rollbacks"):
            parts.append(f"{mh['rollbacks']} rollback(s) to "
                         "last-known-good")
        if mh.get("divergences"):
            parts.append(f"{mh['divergences']} loss divergence(s)")
        if mh.get("grad_explosions"):
            parts.append(f"{mh['grad_explosions']} grad explosion(s)")
        if mh.get("plateaus"):
            parts.append(f"{mh['plateaus']} plateau(s)")
        if mh.get("last_loss") is not None:
            parts.append(f"loss {mh['last_loss']:.4f}")
        if mh.get("last_grad_norm") is not None:
            parts.append(f"grad norm {mh['last_grad_norm']:.4f}")
        lines.append("  model   : " + ("; ".join(parts) or "healthy"))
    ss = report.get("state_sharding")
    if ss:
        # replicated vs sharded per-slot state (docs/sharding.md): is
        # the ZeRO/rules lever actually engaged, and what did it buy?
        for role, kinds in sorted(ss.get("roles", {}).items()):
            parts = []
            for kind in ("params", "opt_state"):
                v = kinds.get(kind, {})
                if "sharded" in v and "replicated" in v:
                    parts.append(f"{kind} {v['sharded']:.3f} vs "
                                 f"{v['replicated']:.3f} MiB/slot")
            ratio = ss.get("savings_ratio", {}).get(role)
            lines.append(
                f"  state   : [{role}] " + ", ".join(parts)
                + (f" — {ratio:.2f}x of replicated"
                   if ratio is not None else ""))
    dp = report.get("dataplane")
    if dp:
        # the feature data-plane story (docs/dataplane.md): what dtype
        # the feature store runs in and what it costs per slot
        for role, v in sorted(dp.get("roles", {}).items()):
            parts = [f"{v.get('dtype', '?')} feats "
                     f"{v.get('mib_per_slot', 0):.3f} MiB/slot"]
            if v.get("backing_mib") is not None:
                parts.append(f"backing {v['backing_mib']:.3f} MiB")
            if v.get("paged_rows") is not None:
                parts.append(f"{v['paged_rows']} row(s) demand-paged")
            lines.append(f"  data    : [{role}] " + ", ".join(parts))
    tn = report.get("tuning")
    if tn:
        # the auto-tuning story (docs/autotune.md): what the run
        # trained with vs its hand-set defaults
        parts = []
        if tn.get("overrides_applied"):
            parts.append("overrides "
                         + ", ".join(tn["overrides_applied"]))
        if tn.get("probes"):
            ran = tn["probes"].get("run", 0)
            skp = tn["probes"].get("ledger_skip", 0)
            parts.append(f"{ran} probe(s)"
                         + (f" (+{skp} ledger-skipped)" if skp else ""))
        if tn.get("best_score") is not None:
            parts.append(f"best score {tn['best_score']:.1f}")
        if tn.get("placements_applied"):
            parts.append(f"{tn['placements_applied']} placement(s) "
                         "applied")
        lines.append("  tuning  : " + ("; ".join(parts) or "active"))
    slo = report.get("serve_slo")
    if slo:
        lines.append(
            f"  serving : {slo['requests']} requests in "
            f"{slo['batches']} batches"
            + (f", occupancy {slo['mean_batch_occupancy']}"
               if slo.get("mean_batch_occupancy") is not None else "")
            + (f", {slo['errors']} errors" if slo.get("errors") else "")
            + (f", {slo['shed']} shed" if slo.get("shed") else "")
            + (f", {slo['slo_breaches']} SLO breach(es)"
               if slo.get("slo_breaches") else ""))
        if slo.get("p50_ms") is not None:
            lines.append(
                f"    latency p50 {slo['p50_ms']}ms  "
                f"p95 {slo['p95_ms']}ms  p99 {slo['p99_ms']}ms "
                "(bucket-interpolated)")
    fleet = report.get("serve_fleet")
    if fleet:
        parts = [f"{len(fleet['per_replica'])} replica(s)"]
        if fleet.get("replicas_up") is not None:
            parts.append(f"{fleet['replicas_up']} up")
        if fleet.get("replica_downs"):
            parts.append(f"{fleet['replica_downs']} down event(s), "
                         f"{fleet.get('replica_regrows', 0)} regrown")
        if fleet.get("failovers"):
            parts.append(f"{fleet['failovers']} failover(s)")
        if fleet.get("retries"):
            parts.append(f"{fleet['retries']} retried forward(s)")
        if fleet.get("shed"):
            parts.append(f"{fleet['shed']} shed")
        lines.append("  fleet   : " + "; ".join(parts))
        if fleet.get("promoted") or fleet.get("rolled_back"):
            lines.append(
                f"    promotions: {fleet.get('promoted', 0)} "
                f"promoted, {fleet.get('rolled_back', 0)} rolled "
                f"back ({fleet.get('canary_mirrors', 0)} canary "
                "mirror(s))")
        for v in (fleet.get("canary_verdicts") or []):
            lines.append(
                f"    canary on {v.get('replica')}: "
                f"{v.get('verdict')} (divergence "
                f"{v.get('divergence')}, nonfinite "
                f"{v.get('nonfinite')})")
    cm = report.get("comm")
    if cm:
        # the network side of the roofline (docs/profiling.md): what
        # the collectives moved, how fast, and how close to the link
        lines.append(
            f"  comm    : {len(cm.get('comm_ops', []))} collective "
            f"kind(s), {cm['comm_bytes_total'] / 2**20:.2f} MiB in "
            f"{cm['comm_seconds']:.3f}s"
            + (f"; top {cm['top_op']} at {cm['top_op_gbps']:.3f} GB/s"
               if cm.get("top_op") else "")
            + (f"; link util {cm['axis_util_max']:.3f}"
               if cm.get("axis_util_max") is not None else "")
            + (f"; overlap {cm['overlap_ratio']}"
               if cm.get("overlap_ratio") is not None else ""))
        for name, v in sorted((cm.get("per_op") or {}).items(),
                              key=lambda kv: -kv[1]["bytes"]):
            lines.append(
                f"    {name}: {v['bytes'] / 2**20:.3f} MiB, "
                f"{v['seconds']:.3f}s, {v['gbps']:.3f} GB/s")
    xr = report.get("xray")
    if xr:
        # the step-anatomy verdict (obs/xray.py): who sets the step
        # time, what category of work they spend it on, and what
        # fixing it would buy
        from dgl_operator_tpu.obs.xray import CATEGORIES
        lines.append(
            f"  xray    : {xr['steps']} step(s), mean critical-path "
            f"step {xr['step_wall_mean_s']:.4f}s; "
            + "  ".join(f"{c} {xr[f'critpath_frac_{c}']:.0%}"
                        for c in CATEGORIES))
        lines.append(
            f"    owner {xr['critical_owner']} "
            f"({xr['critical_owner_frac']:.0%} of steps); what-if: "
            f"comm free −{xr['whatif_comm_free_frac']:.0%}, stalls "
            f"removed −{xr['whatif_stall_free_frac']:.0%}, owner at "
            f"median −{xr['whatif_owner_at_median_frac']:.0%}")
        per = xr.get("periodicity") or {}
        if per.get("every"):
            lines.append(
                f"    periodic spike every {per['every']} step(s)"
                + (f" aligned with {per['aligned_with']}"
                   if per.get("aligned_with") else ""))
    fl = report.get("flight")
    if fl:
        # the incident timeline (obs/flight.py): each dead process's
        # last seconds, leading with the collective left in flight
        lines.append(f"  flight  : {len(fl)} recorder dump(s)")
        for d in fl:
            who = (f"{d.get('host', '?')}:{d.get('pid', '?')}:"
                   f"{d.get('role', '?')}")
            infl = d.get("inflight") or {}
            last = d.get("last_comm") or {}
            if infl:
                what = (f"in flight: {infl.get('op')}@"
                        f"{infl.get('axis')} (program "
                        f"{infl.get('program')}, step "
                        f"{infl.get('step')})")
            elif last:
                what = (f"last comm: {last.get('op')}@"
                        f"{last.get('axis')} (program "
                        f"{last.get('program')}, step "
                        f"{last.get('step')}; window closed)")
            else:
                what = "no collective in flight"
            lines.append(
                f"    {d.get('reason', '?')} on {who} — {what}"
                + f"; {d.get('samples', 0)} sample(s) in window")
    findings = report.get("findings", [])
    if findings:
        lines.append(f"findings ({len(findings)}):")
        for f in findings:
            lines.append(f"  {_SEV_MARK.get(f['severity'], '[?]')} "
                         f"{f['kind']}: {f['message']}")
    else:
        lines.append("findings: none — job looks healthy")
    if report.get("report_path"):
        lines.append(f"report  : {report['report_path']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-doctor",
        description="Diagnose a TPUGraphJob run from its obs/ "
                    "telemetry: merged timeline, skew/straggler "
                    "analytics, stall and lost-host findings.")
    ap.add_argument("obs_dir", nargs="?", default=None,
                    help="obs directory (default: $TPU_OPERATOR_OBS_DIR"
                         ", else <workspace>/obs)")
    ap.add_argument("--workspace", default=None,
                    help="workspace whose obs/ subdir to diagnose")
    ap.add_argument("--json", action="store_true",
                    help="print report.json to stdout instead of text")
    ap.add_argument("--straggler-ratio", type=float,
                    default=DEFAULT_STRAGGLER_RATIO)
    ap.add_argument("--stall-factor", type=float,
                    default=DEFAULT_STALL_FACTOR)
    args = ap.parse_args(argv)
    try:
        obs_dir = resolve_obs_dir(args.obs_dir, args.workspace)
    except SystemExit:
        ap.error("no obs directory: pass one, set "
                 f"{OBS_DIR_ENV}, or use --workspace")
    if not os.path.isdir(obs_dir):
        print(f"tpu-doctor: no such obs directory: {obs_dir}",
              file=sys.stderr)
        return 2
    report = build_report(obs_dir,
                          straggler_ratio=args.straggler_ratio,
                          stall_factor=args.stall_factor)
    if args.json:
        # EXACTLY the persisted job/report.json payload (flag parity
        # with tpu-lint --json / tpu-top --json): report_path is where
        # the file landed, not part of the file — scrapers piping
        # stdout and readers of the artifact must see one schema
        # (pinned in tests/test_quality.py)
        print(json.dumps({k: v for k, v in report.items()
                          if k != "report_path"},
                         indent=2, sort_keys=True))
    else:
        print(render(report))
    critical = any(f["severity"] == "critical"
                   for f in report.get("findings", []))
    return 1 if critical else 0


if __name__ == "__main__":
    raise SystemExit(main())
