"""``tpu-xray`` — distributed step anatomy, critical path, and
what-if attribution from a job's merged telemetry.

The doctor answers "is this job sick"; the profiler answers "how far
from the roofline". Neither answers the question a slow-but-healthy
distributed run actually poses: **which host, doing what, sets the
step time — and what would fixing it buy?** The xray computes that
from artifacts every run already leaves behind:

- per-worker **step windows** from the ``heartbeat`` event stream
  (every trainer emits one per step; consecutive heartbeats fence the
  step's work — SampledTrainer emits no per-step spans, so windows
  must come from events, not the trace);
- per-worker **category time** from the merged ``job/trace.json``
  spans: ``train_compute`` → compute; the comm ledger's
  per-collective spans plus ``halo_exchange_fused`` /
  ``param_gather_fused`` → comm; chaos straggler spans
  (``chaos_step_slow``) → stall; checkpoint spans → ckpt. Attribution
  is **priority-layered and disjoint** (stall ⊃ compute ⊃ comm ⊃
  ckpt; the un-spanned remainder is ``other``), so per-step fractions
  sum to exactly 1.0 — no double-billing an overlapped collective;
- the **critical path**: per step, the worker with the longest
  window owns the step; job step time is the sum of owner walls, and
  ``critpath_frac{category}`` is each category's share of it;
- **what-if estimates** — re-running the per-step max with a category
  (or the dominant owner) removed: "comm free → step −18%",
  "slot 3 at median rate → epoch −11%";
- **periodicity** — every-K-step spikes in the owner wall, aligned
  against ``ckpt_save`` / canary-promotion events.

Timestamps: trace spans are epoch-anchored µs (obs/trace.py), events
epoch seconds — one clock after the collector's skew alignment
(obs/collect.py applies the per-source offsets to BOTH streams), so
windows and spans compare directly.

Stdlib-only — the doctor and the control-plane image import this.
Interval helpers are local on purpose: ``runtime.timers`` has the
same math but ``runtime/__init__`` drags in jax.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from dgl_operator_tpu.benchkeys import XRAY_KEYS
from dgl_operator_tpu.obs import OBS_DIR_ENV
from dgl_operator_tpu.obs.collect import EVENTS_JSONL, job_dir_of
from dgl_operator_tpu.obs.trace import TRACE_JSON

# attribution categories, in render order; layering priority below
CATEGORIES = ("compute", "comm", "stall", "ckpt", "other")
# spans are credited in this order; a lower-priority category only
# gets intervals no higher-priority category covered (stall first:
# an injected straggler drag must never launder itself as compute)
_PRIORITY = ("stall", "compute", "comm", "ckpt")

_COMM_SPAN_NAMES = ("halo_exchange", "halo_exchange_fused",
                    "param_gather_fused")
# trace process rows are named "<label>/<role> (<host>:<pid>)" by the
# collector ("<role> (<host>:<pid>)" pre-merge, obs/__init__.py) —
# parse back to the event worker id host:pid:role
_PROC_RE = re.compile(r"(?:.*/)?(?P<role>[^/]+) "
                      r"\((?P<host>[^:()]+):(?P<pid>\d+)\)$")

DEFAULT_SPIKE_RATIO = 1.5       # owner wall > k * median => spike
_PER_STEP_CAP = 100             # per_step extra rows kept in summary


# ----------------------------------------------------- interval algebra
def _merge(spans: Sequence[Tuple[float, float]]
           ) -> List[Tuple[float, float]]:
    """Union as a sorted disjoint list (empty/inverted spans drop)."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted((a, b) for a, b in spans if b > a):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _clip(spans: Sequence[Tuple[float, float]], lo: float, hi: float
          ) -> List[Tuple[float, float]]:
    return [(max(a, lo), min(b, hi)) for a, b in spans
            if min(b, hi) > max(a, lo)]


def _subtract(spans: Sequence[Tuple[float, float]],
              cover: Sequence[Tuple[float, float]]
              ) -> List[Tuple[float, float]]:
    """``union(spans) - union(cover)`` — both args need not be
    disjoint; the result is."""
    out: List[Tuple[float, float]] = []
    cover = _merge(cover)
    for a, b in _merge(spans):
        cur = a
        for ca, cb in cover:
            if cb <= cur:
                continue
            if ca >= b:
                break
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def _measure(spans: Sequence[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in spans)


# ------------------------------------------------------------- loaders
def _load_events(obs_dir: str) -> List[Dict]:
    from dgl_operator_tpu.obs.analyze import load_events
    path = os.path.join(job_dir_of(obs_dir), EVENTS_JSONL)
    if not os.path.exists(path):
        path = os.path.join(obs_dir, EVENTS_JSONL)
    return load_events(path)


def _load_trace(obs_dir: str) -> List[Dict]:
    from dgl_operator_tpu.obs._io import read_json
    path = os.path.join(job_dir_of(obs_dir), TRACE_JSON)
    if not os.path.exists(path):
        path = os.path.join(obs_dir, TRACE_JSON)
    doc = read_json(path, {})
    return [ev for ev in doc.get("traceEvents", [])
            if isinstance(ev, dict)]


def _clock_offsets(obs_dir: str) -> Dict[str, float]:
    from dgl_operator_tpu.obs._io import read_json
    man = read_json(os.path.join(job_dir_of(obs_dir), "manifest.json"),
                    {})
    off = man.get("clock_offsets_us")
    return off if isinstance(off, dict) else {}


# ----------------------------------------------------- stream digestion
def step_windows(events: Sequence[Dict]
                 ) -> Dict[str, List[Tuple[int, float, float]]]:
    """Per-worker ``(step, t0, t1)`` windows from consecutive
    ``heartbeat`` events: the trainer emits a heartbeat after each
    device call, so the window between heartbeat N-1 and heartbeat N
    fences step N's work on that worker."""
    from dgl_operator_tpu.obs.analyze import worker_id
    beats: Dict[str, List[Tuple[float, int]]] = {}
    for e in events:
        if e.get("event") != "heartbeat" \
                or not isinstance(e.get("step"), (int, float)):
            continue
        beats.setdefault(worker_id(e), []).append(
            (float(e.get("ts") or 0.0), int(e["step"])))
    out: Dict[str, List[Tuple[int, float, float]]] = {}
    for w, seq in beats.items():
        seq.sort()
        wins = [(s1, t0, t1) for (t0, _), (t1, s1)
                in zip(seq, seq[1:]) if t1 > t0]
        if wins:
            out[w] = wins
    return out


def _span_category(name: str, cat: str) -> Optional[str]:
    if cat == "chaos":
        return "stall"
    if name == "train_compute":
        return "compute"
    if cat == "comm" or name in _COMM_SPAN_NAMES:
        return "comm"
    if cat == "ckpt" or name.startswith("ckpt"):
        return "ckpt"
    return None


def spans_by_worker(trace_events: Sequence[Dict]
                    ) -> Dict[str, Dict[str, List[Tuple[float, float]]]]:
    """worker -> category -> [(t0, t1)] in epoch SECONDS, from the
    trace's complete (``ph == "X"``) spans, joined to workers through
    the ``process_name`` metadata rows."""
    pid_worker: Dict[object, str] = {}
    for ev in trace_events:
        if ev.get("ph") != "M" or ev.get("name") != "process_name":
            continue
        m = _PROC_RE.match(str((ev.get("args") or {}).get("name", "")))
        if m:
            pid_worker[ev.get("pid")] = (f"{m.group('host')}:"
                                         f"{m.group('pid')}:"
                                         f"{m.group('role')}")
    out: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
    for ev in trace_events:
        if ev.get("ph") != "X" \
                or not isinstance(ev.get("ts"), (int, float)):
            continue
        w = pid_worker.get(ev.get("pid"))
        if w is None:
            continue
        cat = _span_category(str(ev.get("name", "")),
                             str(ev.get("cat", "")))
        if cat is None:
            continue
        t0 = float(ev["ts"]) / 1e6
        out.setdefault(w, {}).setdefault(cat, []).append(
            (t0, t0 + float(ev.get("dur") or 0.0) / 1e6))
    return out


# ----------------------------------------------------------- attribution
def _attribute_window(t0: float, t1: float,
                      cats: Dict[str, List[Tuple[float, float]]]
                      ) -> Dict[str, float]:
    """Disjoint per-category seconds inside ``[t0, t1]``; sums (with
    ``other``) to exactly the window wall."""
    out: Dict[str, float] = {}
    covered: List[Tuple[float, float]] = []
    for cat in _PRIORITY:
        iv = _clip(cats.get(cat, ()), t0, t1)
        out[cat] = _measure(_subtract(iv, covered))
        covered = _merge(covered + list(iv))
    out["other"] = (t1 - t0) - _measure(covered)
    return out


def xray_report(events: Sequence[Dict],
                trace_events: Sequence[Dict],
                spike_ratio: float = DEFAULT_SPIKE_RATIO
                ) -> Optional[Dict]:
    """The full step-anatomy report (pure — tests feed synthetic
    streams). ``None`` when no worker produced two heartbeats (no
    step telemetry to anatomize)."""
    windows = step_windows(events)
    if not windows:
        return None
    spans = spans_by_worker(trace_events)

    # per (step, worker): wall + disjoint category seconds
    per_step: Dict[int, Dict[str, Dict]] = {}
    for w, wins in windows.items():
        cats = spans.get(w, {})
        for step, t0, t1 in wins:
            rec = _attribute_window(t0, t1, cats)
            rec["wall"] = t1 - t0
            per_step.setdefault(step, {})[w] = rec

    # critical path: the slowest worker owns each step
    steps = sorted(per_step)
    owner_rows: List[Dict] = []
    cat_s = {c: 0.0 for c in CATEGORIES}
    wall_s = 0.0
    owners: Counter = Counter()
    for step in steps:
        ws = per_step[step]
        owner = max(ws, key=lambda w: ws[w]["wall"])
        rec = ws[owner]
        owners[owner] += 1
        wall_s += rec["wall"]
        for c in CATEGORIES:
            cat_s[c] += rec[c]
        owner_rows.append({"step": step, "owner": owner,
                           "wall_s": round(rec["wall"], 6),
                           **{f"{c}_s": round(rec[c], 6)
                              for c in CATEGORIES}})
    if wall_s <= 0:
        return None
    fracs = {c: cat_s[c] / wall_s for c in CATEGORIES}

    # what-if: re-run the per-step max with a category removed —
    # every worker sheds its own category time, then the slowest
    # survivor sets the new step time
    def _without(cat: str) -> float:
        new = sum(max(r["wall"] - r[cat] for r in per_step[s].values())
                  for s in steps)
        return max(0.0, 1.0 - new / wall_s)

    dom, dom_n = owners.most_common(1)[0]

    def _owner_at_median() -> float:
        new = 0.0
        for s in steps:
            walls = {w: r["wall"] for w, r in per_step[s].items()}
            if dom in walls:
                walls[dom] = statistics.median(walls.values())
            new += max(walls.values())
        return max(0.0, 1.0 - new / wall_s)

    whatif = {"comm_free": _without("comm"),
              "stall_free": _without("stall"),
              "owner_at_median": _owner_at_median()}

    # periodicity: every-K-step spikes in the owner wall, aligned
    # against checkpoint / canary-promotion events
    med = statistics.median(r["wall_s"] for r in owner_rows)
    spikes = [r["step"] for r in owner_rows
              if med > 0 and r["wall_s"] > spike_ratio * med]
    every = None
    if len(spikes) >= 3:
        diffs = Counter(b - a for a, b in zip(spikes, spikes[1:]))
        k, n = diffs.most_common(1)[0]
        if k > 0 and n >= 2 and n * 2 >= sum(diffs.values()):
            every = k
    aligned = None
    if spikes:
        ck = {int(e["step"]) for e in events
              if e.get("event") == "ckpt_save"
              and isinstance(e.get("step"), (int, float))}
        ca = {int(e["step"]) for e in events
              if str(e.get("event", "")).startswith("ckpt_promote")
              and isinstance(e.get("step"), (int, float))}
        near = lambda s, ref: any(abs(s - r) <= 1 for r in ref)  # noqa: E731
        if ck and sum(near(s, ck) for s in spikes) * 2 >= len(spikes):
            aligned = "ckpt_save"
        elif ca and sum(near(s, ca) for s in spikes) * 2 >= len(spikes):
            aligned = "ckpt_promote"

    return {
        "steps": len(steps),
        "workers": sorted(windows),
        "step_wall_mean_s": wall_s / len(steps),
        "critpath_frac": fracs,
        "critical_owner": dom,
        "critical_owner_frac": dom_n / len(steps),
        "owner_seconds": {c: cat_s[c] for c in CATEGORIES},
        "whatif": whatif,
        "periodicity": {"spike_steps": spikes, "every": every,
                        "aligned_with": aligned},
        "per_step": owner_rows,
        "owners": dict(owners),
    }


# -------------------------------------------------------------- summary
def xray_summary(obs_dir: str) -> Optional[Dict[str, object]]:
    """Step-anatomy summary of a finished run's obs dir, shaped by the
    pinned ``benchkeys.XRAY_KEYS`` (benchmarks/bench_xray.py tracks it
    as XRAY.json; the doctor xray block renders it). ``None`` when the
    run left no step telemetry — pre-xray obs dirs are unchanged."""
    rep = xray_report(_load_events(obs_dir), _load_trace(obs_dir))
    if rep is None:
        return None
    fr = rep["critpath_frac"]
    out: Dict[str, object] = {
        "steps": rep["steps"],
        "workers": len(rep["workers"]),
        "step_wall_mean_s": round(rep["step_wall_mean_s"], 6),
        "critpath_frac_compute": round(fr["compute"], 4),
        "critpath_frac_comm": round(fr["comm"], 4),
        "critpath_frac_stall": round(fr["stall"], 4),
        "critpath_frac_ckpt": round(fr["ckpt"], 4),
        "critpath_frac_other": round(fr["other"], 4),
        "critical_owner": rep["critical_owner"],
        "critical_owner_frac": round(rep["critical_owner_frac"], 4),
        "whatif_comm_free_frac": round(rep["whatif"]["comm_free"], 4),
        "whatif_stall_free_frac": round(rep["whatif"]["stall_free"], 4),
        "whatif_owner_at_median_frac":
            round(rep["whatif"]["owner_at_median"], 4),
        "periodic_spike_every": rep["periodicity"]["every"],
    }
    assert tuple(out) == XRAY_KEYS
    out["owner_seconds"] = {k: round(v, 6) for k, v
                            in rep["owner_seconds"].items()}
    out["owners"] = rep["owners"]
    out["per_step"] = rep["per_step"][:_PER_STEP_CAP]
    out["periodicity"] = rep["periodicity"]
    out["clock_offsets_us"] = _clock_offsets(obs_dir)
    return out


# ------------------------------------------------------------ live plane
# PhaseTimer bucket -> xray category for the rolling /livez gauge:
# dispatch is the device-call enqueue (compute proxy), exchange the
# halo stage, stall the blocked loop thread; sample is host-side work
# no trace span categorizes — same bucket the trace remainder lands in
_LIVE_PHASE_CAT = {"dispatch": "compute", "exchange": "comm",
                   "stall": "stall", "sample": "other"}


def live_critpath(totals: Optional[Dict[str, float]]
                  ) -> Optional[Dict[str, float]]:
    """Normalized category fractions from a PhaseTimer totals dict —
    the cheap single-worker estimate of ``critpath_frac`` the live
    feed publishes between collections (obs/live.py; the real
    cross-host number needs the merged trace). ``None`` when the
    timer has accumulated nothing yet."""
    acc: Dict[str, float] = {}
    for phase, v in (totals or {}).items():
        cat = _LIVE_PHASE_CAT.get(phase)
        if cat is not None and v and v > 0:
            acc[cat] = acc.get(cat, 0.0) + float(v)
    tot = sum(acc.values())
    if tot <= 0:
        return None
    return {k: round(v / tot, 4) for k, v in sorted(acc.items())}


# ------------------------------------------------------------------ CLI
def render(s: Dict, obs_dir: str) -> str:
    lines = ["tpu-xray — distributed step anatomy"]
    lines.append(f"  obs dir : {obs_dir}")
    lines.append(f"  steps   : {s['steps']} across {s['workers']} "
                 f"worker(s); mean critical-path step "
                 f"{s['step_wall_mean_s']:.4f}s")
    lines.append("  critpath: " + "  ".join(
        f"{c} {s[f'critpath_frac_{c}']:.0%}" for c in CATEGORIES))
    lines.append(f"  owner   : {s['critical_owner']} owns "
                 f"{s['critical_owner_frac']:.0%} of the steps")
    lines.append(
        f"  what-if : comm free → step "
        f"−{s['whatif_comm_free_frac']:.0%};  stalls removed → "
        f"−{s['whatif_stall_free_frac']:.0%};  "
        f"{s['critical_owner']} at median rate → "
        f"−{s['whatif_owner_at_median_frac']:.0%}")
    per = s.get("periodicity") or {}
    if per.get("spike_steps"):
        lines.append(
            f"  periodic: {len(per['spike_steps'])} spike step(s)"
            + (f", every {per['every']} steps" if per.get("every")
               else "")
            + (f" — aligned with {per['aligned_with']}"
               if per.get("aligned_with") else ""))
    off = s.get("clock_offsets_us") or {}
    skewed = {k: v for k, v in off.items() if v}
    if skewed:
        lines.append("  clocks  : skew-corrected "
                     + ", ".join(f"{k} {v:+.0f}µs"
                                 for k, v in sorted(skewed.items())))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-xray",
        description="Reconstruct a run's cross-host step anatomy: "
                    "blame-attributed critical path, what-if "
                    "estimates, and periodic-stall detection.")
    ap.add_argument("obs_dir", nargs="?", default=None,
                    help="obs directory (default: $TPU_OPERATOR_OBS_DIR"
                         ", else <workspace>/obs)")
    ap.add_argument("--workspace", default=None,
                    help="workspace whose obs/ subdir to analyze")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as JSON instead of text")
    args = ap.parse_args(argv)
    from dgl_operator_tpu.obs.doctor import resolve_obs_dir
    try:
        obs_dir = resolve_obs_dir(args.obs_dir, args.workspace)
    except SystemExit:
        ap.error("no obs directory: pass one, set "
                 f"{OBS_DIR_ENV}, or use --workspace")
    if not os.path.isdir(obs_dir):
        print(f"tpu-xray: no such obs directory: {obs_dir}",
              file=sys.stderr)
        return 2
    # a plain single-host obs dir becomes its own job view, exactly
    # like the doctor (the merge also computes clock offsets)
    from dgl_operator_tpu.obs.collect import merge_job_view
    if not os.path.exists(os.path.join(job_dir_of(obs_dir),
                                       EVENTS_JSONL)):
        merge_job_view(job_dir_of(obs_dir),
                       sources=[("local", obs_dir)])
    s = xray_summary(obs_dir)
    if s is None:
        print("tpu-xray: no step telemetry (need >= 2 heartbeats "
              "from at least one worker)", file=sys.stderr)
        return 1
    print(json.dumps(s, indent=2, sort_keys=True) if args.json
          else render(s, obs_dir))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
