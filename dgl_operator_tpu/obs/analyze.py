"""Job-level analytics over the merged obs view: skew, stragglers,
stalls, lost workers, and a live health snapshot.

Systems work on data-parallel training (GSPMD; Automatic Cross-Replica
Sharding, PAPERS.md) shows per-replica imbalance is the dominant
silent perf killer: the job is only as fast as its slowest host, and
nothing in a phase-flip view surfaces WHICH host that is. These
analytics read what the runtime already records — the folded
PhaseTimer buckets (``train_phase_seconds{phase=...}`` per process)
and the per-step ``heartbeat`` events — and answer it:

- :func:`skew_summary` — slowest-vs-median per timing bucket
  (compute/``dispatch``, ``sample``, the owner-layout ``exchange``);
- :func:`analyze_job` — findings with severities: stragglers (worker
  persistently > k × median), stalls (heartbeats stop mid-run), lost
  workers (events end early, no terminal record), injected faults,
  preemptions and resume points;
- :func:`job_health` — a LIVE snapshot from the run's own
  ``events.jsonl`` (no collection needed): per-worker ok / done /
  stalled, consumed by ``Controller.reconcile_until`` so a stalled —
  not just dead — job restarts instead of hanging until deadline.

Worker identity is the obs proc id (``host:pid:role``); the launcher
stamps trainers with a per-rank role (``trainer-<rank>``), so a killed
trainer and its resumed successor are distinct workers sharing a role.

Stdlib-only — the doctor CLI runs in the control-plane image.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Dict, List, Optional

from dgl_operator_tpu.obs._io import read_json
from dgl_operator_tpu.obs.collect import EVENTS_JSONL, METRICS_JSON, \
    job_dir_of

# findings severity order (reports sort most-severe first)
SEVERITIES = ("critical", "warning", "info")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

DEFAULT_STRAGGLER_RATIO = 1.5   # slowest > k * median => straggler
DEFAULT_STALL_FACTOR = 5.0      # silent for > N * median step time
DEFAULT_STALL_GRACE_S = 1.0     # floor under the stall window
DEFAULT_HBM_DRIFT_FRAC = 0.20   # measured > (1 + f) * predicted HBM

# events that prove a worker is making progress
_LIVENESS_EVENTS = ("heartbeat", "train_step", "epoch", "epoch_summary",
                    "eval", "train_resume", "ckpt_save")
# events that END a worker's story cleanly (silence afterwards is fine)
_TERMINAL_EVENTS = ("train_done", "preempted")


def worker_id(rec: Dict) -> str:
    """The obs proc id of an event's emitter."""
    return (f"{rec.get('host', '?')}:{rec.get('pid', '?')}:"
            f"{rec.get('role', '?')}")


def load_events(path: str) -> List[Dict]:
    """Tolerant JSONL read: skips torn/garbage lines (a killed writer
    may leave a partial tail)."""
    out: List[Dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


# ------------------------------------------------------------- skew
def skew_summary(series: Dict[str, Dict[str, float]]) -> Dict[str, Dict]:
    """Per-bucket imbalance: ``series`` maps bucket -> {subject ->
    seconds} (subjects are workers for job skew, steps for the bench's
    per-step skew). Returns per bucket the median, the slowest subject
    and the slowest/median ratio.

    Zero-median contract (ISSUE 9 satellite): a bucket whose median is
    0 (e.g. an all-zero bytes-only bucket, or a phase no worker spent
    time in) reports ``ratio: None`` — undefined, not ``inf``. Every
    downstream consumer (the straggler findings below, the doctor's
    skew lines, the autotune probe scorer) must guard before comparing
    a ratio; regression-pinned in tests/test_autotune.py with an
    all-zero bucket."""
    out: Dict[str, Dict] = {}
    for bucket in sorted(series):
        per = {k: float(v) for k, v in series[bucket].items()
               if v is not None}
        if not per:
            continue
        med = statistics.median(per.values())
        slowest = max(per, key=per.get)
        out[bucket] = {
            "n": len(per),
            "median_s": round(med, 6),
            "slowest": slowest,
            "slowest_s": round(per[slowest], 6),
            "ratio": (round(per[slowest] / med, 3) if med > 0 else None),
        }
    return out


def phase_seconds_by_worker(procs: Dict[str, dict],
                            family: str = "train_phase_seconds"
                            ) -> Dict[str, Dict[str, float]]:
    """bucket -> worker -> accumulated seconds, from each process's
    folded PhaseTimer histogram (the ``sum`` of its per-epoch
    observations)."""
    series: Dict[str, Dict[str, float]] = {}
    for proc_id, snap in procs.items():
        fam = (snap or {}).get(family)
        if not isinstance(fam, dict):
            continue
        for s in fam.get("samples", []):
            bucket = s.get("labels", {}).get("phase")
            if bucket is None:
                continue
            series.setdefault(bucket, {})[proc_id] = \
                float(s.get("sum", 0.0))
    return series


def comm_slot_seconds_by_slot(procs: Dict[str, dict]
                              ) -> Dict[str, Dict[str, float]]:
    """``op@axis`` -> mesh slot -> accumulated readiness-lag seconds,
    from the comm watcher's per-slot skew counter (obs/comm.py
    ``comm_slot_seconds``) — the collective-granularity straggler
    series: subjects are mesh SLOTS, not workers, so a slow link or
    chip shows up even when every host process looks healthy."""
    series: Dict[str, Dict[str, float]] = {}
    for snap in procs.values():
        fam = (snap or {}).get("comm_slot_seconds")
        if not isinstance(fam, dict):
            continue
        for s in fam.get("samples", []):
            lb = s.get("labels", {})
            op, axis, slot = lb.get("op"), lb.get("axis"), \
                lb.get("slot")
            if op is None or slot is None:
                continue
            bucket = f"{op}@{axis}"
            series.setdefault(bucket, {})
            series[bucket][f"slot {slot}"] = \
                series[bucket].get(f"slot {slot}", 0.0) \
                + float(s.get("value", 0.0))
    return series


DEFAULT_STARVED_FRAC = 0.25     # stall > 25% of loop-thread time


def pipeline_summary(procs: Dict[str, dict],
                     starved_frac: float = DEFAULT_STARVED_FRAC
                     ) -> Optional[Dict]:
    """Input-pipeline starvation verdict from the folded PhaseTimer
    buckets (ISSUE 7): ``stall`` is loop-thread time blocked waiting on
    a pipeline stage (sampler futures, staged halo exchanges) —
    sampler-starved time, not staging work. The verdict compares it to
    the loop thread's total accounted time (``stall + sample +
    dispatch``): **starved** means the device waited on the input plane
    (raise ``num_samplers`` / ``prefetch``); **saturated** means the
    pipeline kept ahead of compute. ``exchange_s`` (the decoupled halo
    stage, measured off-thread) rides along for context. ``None`` when
    no training process recorded pipeline buckets."""
    series = phase_seconds_by_worker(procs)
    if "stall" not in series and "sample" not in series:
        return None
    stall = sum(series.get("stall", {}).values())
    sample = sum(series.get("sample", {}).values())
    dispatch = sum(series.get("dispatch", {}).values())
    exchange = sum(series.get("exchange", {}).values())
    busy = stall + sample + dispatch
    frac = stall / busy if busy > 0 else 0.0
    return {"stall_s": round(stall, 3), "sample_s": round(sample, 3),
            "dispatch_s": round(dispatch, 3),
            "exchange_s": round(exchange, 3),
            "stall_frac": round(frac, 4),
            "verdict": "starved" if frac > starved_frac
            else "saturated"}


def hardware_summary(procs: Dict[str, dict]) -> Optional[Dict]:
    """Hardware-utilization roll-up from the per-process snapshots
    (the gauges ``obs/prof.py`` emits every heartbeat window): the
    job-wide MFU (max across trainer processes — each scores its own
    devices against the same peak table), the binding roofline
    resource, the worst per-device HBM watermark vs the analytic
    prediction, and the compile bill. ``None`` when no process carried
    the profiler (pre-prof runs are unchanged)."""
    mfu = None
    fracs: Dict[str, float] = {}
    wm, wm_dev, pred = None, None, None
    compiles = 0
    compile_s = 0.0
    for snap in procs.values():
        snap = snap or {}
        for s in (snap.get("train_mfu") or {}).get("samples", []):
            v = float(s["value"])
            mfu = v if mfu is None else max(mfu, v)
        for s in (snap.get("train_roofline_frac") or {}).get(
                "samples", []):
            b = s.get("labels", {}).get("bound", "?")
            fracs[b] = max(fracs.get(b, 0.0), float(s["value"]))
        for s in (snap.get("train_hbm_watermark_mib") or {}).get(
                "samples", []):
            v = float(s["value"])
            if wm is None or v > wm:
                wm, wm_dev = v, s.get("labels", {}).get("device")
        for s in (snap.get("train_hbm_predicted_mib") or {}).get(
                "samples", []):
            v = float(s["value"])
            pred = v if pred is None else max(pred, v)
        for s in (snap.get("jit_compiles_total") or {}).get(
                "samples", []):
            compiles += int(s.get("value", 0))
        for s in (snap.get("jit_compile_seconds") or {}).get(
                "samples", []):
            compile_s += float(s.get("sum", 0.0))
    if mfu is None and wm is None and not compiles:
        return None
    bound = max(fracs, key=fracs.get) if fracs else None
    return {
        "mfu": mfu,
        "roofline_bound": bound,
        "roofline_fracs": {k: round(v, 6)
                           for k, v in sorted(fracs.items())},
        "hbm_watermark_mib": wm,
        "hbm_watermark_device": wm_dev,
        "hbm_predicted_mib": pred,
        "jit_compiles": compiles,
        "jit_compile_seconds": round(compile_s, 3),
    }


# -------------------------------------------------------------- report
def _finding(kind: str, severity: str, subject: str, message: str,
             **evidence) -> Dict:
    assert severity in _SEV_RANK, severity
    return {"kind": kind, "severity": severity, "subject": subject,
            "message": message, "evidence": evidence}


def _liveness(events: List[Dict]) -> Dict[str, Dict]:
    """Per-worker liveness ledger: heartbeat timestamps/steps, last
    event of any kind, and the terminal event (if one ended the
    worker's story)."""
    workers: Dict[str, Dict] = {}
    for e in events:
        w = worker_id(e)
        rec = workers.setdefault(w, {"hb_ts": [], "steps": [],
                                     "last_ts": 0.0, "first_ts": None,
                                     "terminal": None, "dead": None,
                                     "numerics": None,
                                     "n_events": 0})
        ts = float(e.get("ts") or 0.0)
        rec["n_events"] += 1
        rec["last_ts"] = max(rec["last_ts"], ts)
        if rec["first_ts"] is None:
            rec["first_ts"] = ts
        if e.get("event") in _LIVENESS_EVENTS:
            rec["hb_ts"].append(ts)
            if isinstance(e.get("step"), (int, float)):
                rec["steps"].append(int(e["step"]))
        if e.get("event") in _TERMINAL_EVENTS:
            rec["terminal"] = {"event": e["event"],
                               "step": e.get("step"), "ts": ts}
        if e.get("event") == "numerics_fault" \
                and e.get("action") != "warn":
            # the sentry halted this worker on non-finite state
            # (action warn keeps training and must not read unhealthy)
            rec["numerics"] = {"step": e.get("step"),
                               "partition": e.get("partition"),
                               "kind": e.get("kind"), "ts": ts}
        if e.get("event") == "host_died":
            # permanent loss (chaos host:die / elastic detection):
            # host_name is the LOGICAL hostfile host — on a shared-fs
            # fabric every process reports the same real hostname, so
            # the event must carry the identity elasticity plans with
            rec["dead"] = {"step": e.get("step"), "ts": ts,
                           "host_name": e.get("host_name")}
    return workers


def _median_interval(ts: List[float], floor: float) -> float:
    if len(ts) < 2:
        return floor
    ts = sorted(ts)
    gaps = [b - a for a, b in zip(ts, ts[1:])]
    return max(statistics.median(gaps), 1e-6)


def analyze_job(obs_dir: Optional[str] = None, *,
                events: Optional[List[Dict]] = None,
                procs: Optional[Dict[str, dict]] = None,
                straggler_ratio: float = DEFAULT_STRAGGLER_RATIO,
                stall_factor: float = DEFAULT_STALL_FACTOR,
                stall_grace_s: float = DEFAULT_STALL_GRACE_S) -> Dict:
    """Analyze a job's merged telemetry into a structured report:
    ``{"run", "summary", "skew", "findings"}``. Reads the ``obs/job/``
    view when ``obs_dir`` is given (falling back to the plain obs dir
    for single-host runs); pass ``events``/``procs`` directly in
    tests."""
    if obs_dir is not None:
        jd = job_dir_of(obs_dir)
        epath = os.path.join(jd, EVENTS_JSONL)
        if not os.path.exists(epath):
            jd = obs_dir
            epath = os.path.join(jd, EVENTS_JSONL)
        if events is None:
            events = load_events(epath)
        if procs is None:
            procs = read_json(os.path.join(jd, METRICS_JSON),
                              {}).get("procs") or {}
    events = events or []
    procs = procs or {}

    findings: List[Dict] = []
    run_id = next((e.get("run") for e in events if e.get("run")), None)
    end_ts = max((float(e.get("ts") or 0.0) for e in events),
                 default=0.0)

    # ---- summary ----------------------------------------------------
    by_kind: Dict[str, List[Dict]] = {}
    for e in events:
        by_kind.setdefault(str(e.get("event")), []).append(e)

    phases = []
    titles = {e.get("phase"): e.get("title")
              for e in by_kind.get("phase_start", [])}
    for e in by_kind.get("phase_finish", []):
        phases.append({"phase": e.get("phase"),
                       "title": titles.get(e.get("phase")),
                       "seconds": e.get("seconds")})
    skipped = [{"phase": e.get("phase"), "title": e.get("title")}
               for e in by_kind.get("phase_skip", [])]

    faults = []
    for e in by_kind.get("chaos_fault", []):
        faults.append({"verb": e.get("verb"), "action": e.get("action"),
                       "host": e.get("host"), "rule": e.get("rule")})
    for e in by_kind.get("chaos_train_kill", []):
        faults.append({"verb": "train", "action": "kill",
                       "step": e.get("step"), "worker": worker_id(e)})

    preemptions = [{"worker": worker_id(e), "step": e.get("step")}
                   for e in by_kind.get("preempted", [])]
    resumes = [{"worker": worker_id(e), "step": e.get("step")}
               for e in by_kind.get("train_resume", [])]

    live = _liveness(events)
    workers = sorted(w for w, rec in live.items() if rec["hb_ts"])
    steps = [s for rec in live.values() for s in rec["steps"]]

    summary = {
        "events": len(events),
        "workers": workers,
        "phases": phases,
        "phases_skipped": skipped,
        "faults_injected": faults,
        "retries": len(by_kind.get("fabric_retry", [])),
        "retry_exhausted": len(by_kind.get("fabric_retry_exhausted",
                                           [])),
        "preemptions": preemptions,
        "resume_points": resumes,
        "epochs": len(by_kind.get("epoch", [])),
        "last_step": max(steps) if steps else None,
        "lock_breaks": len(by_kind.get("obs_lock_broken", [])),
        "slo_breaches": len(by_kind.get("slo_breach", [])),
        "failure_collections": len(by_kind.get("obs_collect_on_failure",
                                               [])),
        "jit_compiles": len(by_kind.get("jit_compile", [])),
        "host_deaths": len(by_kind.get("host_died", [])),
        "elastic_shrinks": len(by_kind.get("elastic_shrink", [])),
        "elastic_regrows": len(by_kind.get("elastic_regrow", [])),
        "ckpt_fallbacks": len(by_kind.get("ckpt_restore_fallback", [])),
        "fence_rejections": len(by_kind.get("ckpt_fence_rejected", [])),
        "numerics_faults": len(by_kind.get("numerics_fault", [])),
        "numerics_rollbacks": len(by_kind.get("numerics_rollback", [])),
    }

    # ---- elasticity roll-up (ISSUE 13, docs/elasticity.md) ----------
    shrinks = by_kind.get("elastic_shrink", [])
    regrows = by_kind.get("elastic_regrow", [])
    deaths = [{"worker": worker_id(e), "host": e.get("host_name"),
               "step": e.get("step"),
               "ts": float(e.get("ts") or 0.0)}
              for e in by_kind.get("host_died", [])]
    elasticity = None
    if deaths or shrinks or regrows or summary["ckpt_fallbacks"] \
            or summary["fence_rejections"]:
        epochs = [e.get("epoch") for e in shrinks + regrows
                  if isinstance(e.get("epoch"), int)]
        elasticity = {
            "host_deaths": [{k: v for k, v in d.items() if k != "ts"}
                            for d in deaths],
            "dead_hosts": sorted({d["host"] for d in deaths
                                  if d["host"]}),
            "shrinks": len(shrinks),
            "regrows": len(regrows),
            "width": (shrinks[-1].get("width") if shrinks else None),
            "full_width": (shrinks[-1].get("full_width")
                           if shrinks else None),
            "last_epoch": (max(epochs) if epochs else None),
            "fence_rejections": summary["fence_rejections"],
            "ckpt_fallbacks": summary["ckpt_fallbacks"],
        }

    # ---- model health (ISSUE 15, obs/quality.py) --------------------
    from dgl_operator_tpu.obs.quality import model_health_summary
    model_health = model_health_summary(events, procs)

    # recovery signal for the numerics findings: a rollback relaunch
    # or a resumed trainer at/after the fault means the automated
    # response handled it — warning, not an open critical
    recovery_ts = [float(e.get("ts") or 0.0)
                   for e in (by_kind.get("numerics_rollback", [])
                             + by_kind.get("train_resume", []))]
    for e in by_kind.get("numerics_fault", []):
        ts = float(e.get("ts") or 0.0)
        recovered = any(r >= ts for r in recovery_ts)
        sev = ("warning" if recovered or e.get("action") == "warn"
               else "critical")
        part = e.get("partition")
        msg = (f"non-finite training state ({e.get('kind')}) at step "
               f"{e.get('step')}"
               + (f" on partition {part}" if part is not None else ""))
        if recovered:
            msg += ("; rolled back to the last-known-good checkpoint "
                    "and resumed")
        elif e.get("action") == "warn":
            msg += ("; quality_action=warn — training continued on "
                    "bad state (inspect the trajectory)")
        else:
            msg += ("; trainer halted — relaunch with tpurun "
                    "--numerics-retries or inspect the quarantined "
                    "checkpoints")
        findings.append(_finding(
            "numerics_fault", sev, worker_id(e), msg,
            step=e.get("step"), partition=part,
            fault_kind=e.get("kind"), recovered=recovered))
    for kind, label in (("loss_divergence", "loss diverged"),
                        ("grad_explosion", "gradient norm exploded")):
        evs = by_kind.get(kind, [])
        if not evs:
            continue
        last = evs[-1]
        detail = (f"z={last.get('z')} (max {last.get('z_max')})"
                  if kind == "loss_divergence" else
                  f"{last.get('ratio')}x the rolling median "
                  f"(max {last.get('ratio_max')}x)")
        findings.append(_finding(
            kind, "warning", worker_id(last),
            f"{label} at step {last.get('step')}: {detail}"
            + (f" — {len(evs)} detection(s)" if len(evs) > 1 else ""),
            step=last.get("step"), count=len(evs)))
    for e in by_kind.get("loss_plateau", []):
        findings.append(_finding(
            "loss_plateau", "info", worker_id(e),
            f"loss plateaued at step {e.get('step')} (range "
            f"{e.get('spread')} over {e.get('window')} steps)",
            step=e.get("step")))

    # ---- findings: faults / failures -------------------------------
    rule_counts: Dict[str, int] = {}
    for f in faults:
        key = f.get("rule") or f"train:kill:{f.get('step')}"
        rule_counts[key] = rule_counts.get(key, 0) + 1
    for f in faults:
        key = f.get("rule") or f"train:kill:{f.get('step')}"
        if key not in rule_counts:
            continue
        n = rule_counts.pop(key)
        subject = f.get("host") or f.get("worker") or "?"
        findings.append(_finding(
            "fault_injected", "info", subject,
            f"chaos plan delivered {key} on {subject}"
            + (f" ({n} times)" if n > 1 else ""),
            rule=key, count=n, step=f.get("step")))
    for e in by_kind.get("fabric_retry_exhausted", []):
        findings.append(_finding(
            "retry_exhausted", "critical", worker_id(e),
            f"fabric verb {e.get('verb')} ran out of retry attempts: "
            f"{str(e.get('error'))[:120]}",
            verb=e.get("verb"), attempts=e.get("attempts")))
    for e in by_kind.get("phase_error", []):
        # a phase error the elastic plane recovered (a shrink followed
        # it and the phase later finished) — or the model-health plane
        # rolled back (numerics_rollback, same contract) — is a
        # handled event, not an open incident; critical only when
        # nothing absorbed it
        ts = float(e.get("ts") or 0.0)
        reshaped = any(float(s.get("ts") or 0.0) >= ts
                       for s in shrinks)
        rolled_back = any(float(r.get("ts") or 0.0) >= ts
                          for r in by_kind.get("numerics_rollback",
                                               []))
        refinished = any(f.get("phase") == e.get("phase")
                         and float(f.get("ts") or 0.0) >= ts
                         for f in by_kind.get("phase_finish", []))
        handled = (reshaped or rolled_back) and refinished
        findings.append(_finding(
            "phase_failed", "warning" if handled else "critical",
            worker_id(e),
            f"workflow phase {e.get('phase')} raised"
            + ("; recovered by elastic shrink + relaunch"
               if handled and reshaped else
               "; recovered by numerics rollback + relaunch"
               if handled else ""),
            phase=e.get("phase"), recovered=handled))

    # ---- findings: preempted / lost / stalled workers --------------
    for p in preemptions:
        resumed = next((r for r in resumes
                        if r["step"] is not None and p["step"] is not None
                        and r["step"] >= p["step"]), None)
        sev = "warning" if resumed else "critical"
        msg = (f"worker {p['worker']} lost to preemption/kill at step "
               f"{p['step']}")
        if resumed:
            msg += (f"; resumed at step {resumed['step']} by "
                    f"{resumed['worker']}")
        findings.append(_finding("worker_lost", sev, p["worker"], msg,
                                 step=p["step"],
                                 resumed_step=(resumed or {}).get("step"),
                                 resumed_by=(resumed or {}).get("worker")))
    # ---- findings: dead hosts / elastic edges ----------------------
    for d in deaths:
        reshaped = any(float(s.get("ts") or 0.0) >= d["ts"]
                       for s in shrinks)
        sev = "warning" if reshaped else "critical"
        msg = (f"host {d['host'] or '?'} died permanently at step "
               f"{d['step']} (worker {d['worker']})")
        if reshaped:
            msg += ("; elastic shrink re-placed its partitions over "
                    "the surviving hosts")
        else:
            msg += ("; no elastic shrink followed — the job cannot "
                    "finish without re-placement (run the driver "
                    "with --elastic, docs/elasticity.md)")
        findings.append(_finding(
            "host_died", sev, d["worker"], msg, step=d["step"],
            host=d["host"], reshaped=reshaped))
    if summary["ckpt_fallbacks"]:
        last = by_kind["ckpt_restore_fallback"][-1]
        findings.append(_finding(
            "ckpt_fallback", "warning", worker_id(last),
            f"{summary['ckpt_fallbacks']} checkpoint restore(s) "
            "skipped a corrupt/partial archive and fell back to the "
            f"last-known-good (latest: step {last.get('step')}, "
            f"{str(last.get('error'))[:120]})",
            count=summary["ckpt_fallbacks"], step=last.get("step")))
    if summary["fence_rejections"]:
        last = by_kind["ckpt_fence_rejected"][-1]
        findings.append(_finding(
            "ckpt_fence_rejected", "info", worker_id(last),
            f"{summary['fence_rejections']} zombie checkpoint "
            "publication(s) rejected by the fencing token (epoch "
            f"{last.get('epoch')} vs current "
            f"{last.get('current_epoch')}) — newer state survived, "
            "the fence doing its job",
            count=summary["fence_rejections"]))

    preempted_ids = {p["worker"] for p in preemptions}
    dead_ids = {d["worker"] for d in deaths}
    # a numerics-halted worker ends its story at the fault — the
    # numerics_fault finding owns that verdict; a stalled finding on
    # top would double-report the same incident
    numerics_ids = {worker_id(e)
                    for e in by_kind.get("numerics_fault", [])
                    if e.get("action") != "warn"}
    for w in workers:
        rec = live[w]
        if rec["terminal"] is not None or w in preempted_ids \
                or w in dead_ids or w in numerics_ids:
            continue
        med = _median_interval(rec["hb_ts"], stall_grace_s)
        window = max(stall_factor * med, stall_grace_s)
        silent = end_ts - max(rec["hb_ts"])
        if silent > window:
            findings.append(_finding(
                "worker_stalled", "critical", w,
                f"worker {w} went silent {silent:.1f}s before the end "
                f"of the run (median step interval {med:.3f}s, no "
                "terminal event) — stalled or lost",
                silent_s=round(silent, 3),
                median_interval_s=round(med, 6),
                last_step=(max(rec["steps"]) if rec["steps"] else None)))

    # ---- findings: stragglers from the folded phase buckets --------
    skew = skew_summary(phase_seconds_by_worker(procs))
    for bucket, s in skew.items():
        # the explicit zero-median guard: ratio is None for all-zero
        # buckets and must never be compared (skew_summary contract)
        if s["n"] >= 2 and s["ratio"] is not None and \
                s["ratio"] > straggler_ratio:
            findings.append(_finding(
                "straggler", "warning", s["slowest"],
                f"worker {s['slowest']} spent {s['slowest_s']:.3f}s in "
                f"'{bucket}' vs a median of {s['median_s']:.3f}s "
                f"({s['ratio']}x; threshold {straggler_ratio}x)",
                bucket=bucket, ratio=s["ratio"],
                median_s=s["median_s"], slowest_s=s["slowest_s"]))

    # ---- findings: per-collective stragglers (comm watcher skew) ----
    # same skew machinery, finer grain: subjects are mesh slots and
    # buckets are op@axis from the comm ledger, so the finding names
    # the collective in flight ("slot 3 is 2.1x median on
    # halo_a2a_serve@dp") instead of blaming a whole phase
    comm_skew = skew_summary(comm_slot_seconds_by_slot(procs))
    for bucket, s in comm_skew.items():
        if s["n"] >= 2 and s["ratio"] is not None and \
                s["ratio"] > straggler_ratio:
            findings.append(_finding(
                "comm_straggler", "warning", s["slowest"],
                f"{s['slowest']} is {s['ratio']}x median on {bucket} "
                f"({s['slowest_s']:.3f}s vs {s['median_s']:.3f}s; "
                f"threshold {straggler_ratio}x)",
                bucket=bucket, ratio=s["ratio"],
                median_s=s["median_s"], slowest_s=s["slowest_s"]))

    # ---- findings: SLO breaches (live monitor, obs/slo.py) ----------
    # one finding per target: the latest breach's numbers plus the
    # breach count — a recovered breach still warrants a look
    slo_by_target: Dict[str, List[Dict]] = {}
    for e in by_kind.get("slo_breach", []):
        slo_by_target.setdefault(str(e.get("target")), []).append(e)
    for target, evs in sorted(slo_by_target.items()):
        last = evs[-1]
        recovered = any(r.get("target") == target
                        for r in by_kind.get("slo_recovered", []))
        shed = bool(by_kind.get("serve_shed_start"))
        findings.append(_finding(
            "slo_breach", "warning", worker_id(last),
            f"SLO target {target} breached "
            f"({last.get('value')} vs threshold "
            f"{last.get('threshold')}, burn {last.get('burn_rate')})"
            + (f" {len(evs)} time(s)" if len(evs) > 1 else "")
            + ("; load shedding engaged" if shed else "")
            + ("; recovered" if recovered else ""),
            target=target, count=len(evs), value=last.get("value"),
            threshold=last.get("threshold"),
            burn_rate=last.get("burn_rate"), recovered=recovered))

    # ---- findings: recompilation in steady state --------------------
    # the silent 10x killer the padding invariant exists to prevent
    # (runtime/loop.py pad contract; obs/prof.py instrument_jit marks
    # every compile past a function's warmup calls `steady=True`) —
    # now enforced with data: any steady compile is critical
    steady_by_fn: Dict[str, List[Dict]] = {}
    for e in by_kind.get("jit_compile", []):
        if e.get("steady"):
            steady_by_fn.setdefault(str(e.get("fn")), []).append(e)
    for fn, evs in sorted(steady_by_fn.items()):
        last = evs[-1]
        secs = sum(float(e.get("seconds") or 0.0) for e in evs)
        findings.append(_finding(
            "steady_state_recompile", "critical", worker_id(last),
            f"jitted function '{fn}' recompiled {len(evs)} time(s) "
            f"after warmup ({secs:.2f}s of compile stall) — a shape "
            "is churning past the static-padding contract "
            "(runtime/loop.py); every distinct shape costs a full "
            "XLA compile mid-training",
            fn=fn, count=len(evs), compile_seconds=round(secs, 3),
            last_call=last.get("call")))

    # ---- findings: measured vs predicted HBM drift ------------------
    hw = hardware_summary(procs)
    if hw is not None:
        pred = hw.get("hbm_predicted_mib")
        meas = hw.get("hbm_watermark_mib")
        if pred and meas and meas > pred * (1.0 + DEFAULT_HBM_DRIFT_FRAC):
            findings.append(_finding(
                "hbm_drift", "warning", hw.get("hbm_watermark_device",
                                               "job"),
                f"measured HBM watermark {meas:.1f} MiB exceeds the "
                f"analytic hbm_budget model's {pred:.1f} MiB by "
                f"{meas / pred - 1.0:.0%} (> "
                f"{DEFAULT_HBM_DRIFT_FRAC:.0%} tolerance) — the "
                "budget model is missing a resident buffer (staging "
                "depth? cache? donation regression)",
                watermark_mib=meas, predicted_mib=pred,
                drift_frac=round(meas / pred - 1.0, 4)))

    # ---- findings: input-pipeline starvation ------------------------
    pipeline = pipeline_summary(procs)
    if pipeline is not None and pipeline["verdict"] == "starved":
        findings.append(_finding(
            "pipeline_starved", "info", "job",
            f"input pipeline starved: {pipeline['stall_s']}s blocked "
            f"on sampler/exchange stages vs {pipeline['dispatch_s']}s "
            f"dispatching ({pipeline['stall_frac']:.0%} of loop time) "
            "— raise num_samplers or prefetch",
            **{k: v for k, v in pipeline.items() if k != "verdict"}))

    # ---- findings: step anatomy (ISSUE 20, obs/xray.py) -------------
    # the critical-path view answers what the phase-bucket straggler
    # finding cannot: not just "who is slow" but what owning the
    # critical path COSTS — and whether the spikes are periodic
    xray = None
    if obs_dir is not None:
        from dgl_operator_tpu.obs.xray import xray_summary
        try:
            xray = xray_summary(obs_dir)
        except (OSError, ValueError):
            xray = None
    if xray:
        if len(workers) >= 2 and xray["critical_owner_frac"] > 0.6 \
                and xray["whatif_owner_at_median_frac"] >= 0.05:
            findings.append(_finding(
                "xray_straggler", "warning", xray["critical_owner"],
                f"worker {xray['critical_owner']} owns "
                f"{xray['critical_owner_frac']:.0%} of the critical "
                f"path; at the median per-step rate the job would run "
                f"{xray['whatif_owner_at_median_frac']:.0%} faster "
                "(tpu-xray)",
                owner_frac=xray["critical_owner_frac"],
                whatif_frac=xray["whatif_owner_at_median_frac"]))
        if xray["critpath_frac_stall"] >= 0.10:
            findings.append(_finding(
                "xray_stall", "warning", xray["critical_owner"],
                f"{xray['critpath_frac_stall']:.0%} of the critical "
                "path is stall time; removing it would cut step time "
                f"{xray['whatif_stall_free_frac']:.0%} (tpu-xray)",
                stall_frac=xray["critpath_frac_stall"],
                whatif_frac=xray["whatif_stall_free_frac"]))
        per = xray.get("periodicity") or {}
        if per.get("every"):
            findings.append(_finding(
                "xray_periodic_stall", "info", "job",
                f"critical-path step time spikes every "
                f"{per['every']} steps"
                + (f", aligned with {per['aligned_with']} spans"
                   if per.get("aligned_with") else "")
                + " (tpu-xray)",
                every=per["every"], spikes=len(per.get("spike_steps",
                                                       [])),
                aligned_with=per.get("aligned_with")))

    findings.sort(key=lambda f: (_SEV_RANK[f["severity"]], f["kind"],
                                 f["subject"]))
    return {"run": run_id, "summary": summary, "skew": skew,
            "pipeline": pipeline, "hardware": hw,
            "elasticity": elasticity, "model_health": model_health,
            "xray": xray, "findings": findings}


# -------------------------------------------------------------- health
def job_health(obs_dir: str, now: Optional[float] = None,
               stall_factor: float = DEFAULT_STALL_FACTOR,
               stall_grace_s: float = DEFAULT_STALL_GRACE_S) -> Dict:
    """LIVE job health from the run's own ``events.jsonl`` (append-only
    — readable mid-run with no collection): per-worker status ``ok`` /
    ``done`` / ``stalled`` derived from the per-step heartbeats. A
    worker is stalled when it has been silent for more than
    ``stall_factor`` × its median heartbeat interval (floored at
    ``stall_grace_s``) and no terminal event ended its story.
    ``healthy`` is False iff any worker is stalled — the signal
    ``Controller.reconcile_until`` turns into a restart."""
    now = time.time() if now is None else now
    events = load_events(os.path.join(obs_dir, EVENTS_JSONL))
    live = _liveness(events)
    # a numerics fault the rollback plane already handled (a rollback
    # relaunch or a resumed trainer at/after the fault) ended that
    # worker's story — its successor carries the job
    recovery_ts = [float(e.get("ts") or 0.0) for e in events
                   if e.get("event") in ("numerics_rollback",
                                         "train_resume")]
    workers: Dict[str, Dict] = {}
    stalled: List[str] = []
    dead: List[str] = []
    dead_hosts: List[str] = []
    numerics: List[str] = []
    for w, rec in sorted(live.items()):
        if not rec["hb_ts"]:
            continue   # driver/controller processes have no heartbeat
        last = max(rec["hb_ts"])
        med = _median_interval(rec["hb_ts"], stall_grace_s)
        window = max(stall_factor * med, stall_grace_s)
        if rec["dead"] is not None:
            # a host_died worker is PERMANENTLY gone — not "stalled"
            # (which a restart might heal in place): the controller
            # restarts with reason HostDead and the elastic driver
            # re-places its partitions (docs/elasticity.md)
            status = "dead"
            dead.append(w)
            hn = rec["dead"].get("host_name")
            if hn and hn not in dead_hosts:
                dead_hosts.append(hn)
        elif rec["numerics"] is not None:
            # the numerics sentry halted this worker (obs/quality.py):
            # the controller restarts with reason NumericsFault unless
            # a rollback/resume already handled it
            handled = any(ts >= rec["numerics"]["ts"]
                          for ts in recovery_ts)
            status = "rolled_back" if handled else "numerics_fault"
            if not handled:
                numerics.append(w)
        elif rec["terminal"] is not None:
            status = "done"
        elif now - last > window:
            status = "stalled"
            stalled.append(w)
        else:
            status = "ok"
        workers[w] = {
            "status": status,
            "last_step": (max(rec["steps"]) if rec["steps"] else None),
            "last_heartbeat_ts": last,
            "silent_s": round(max(now - last, 0.0), 3),
            "stall_window_s": round(window, 3),
            "terminal": rec["terminal"],
            "dead": rec["dead"],
            "numerics": rec["numerics"],
        }
    # serving-fleet replica ledger (serve/router.py): a replica is
    # down when its last down/regrow event says so. Deliberately NOT
    # folded into `healthy` — the router already drained its traffic
    # to survivors, so the JOB is fine; the controller restarts the
    # replica with its own reason (ReplicaDead) instead
    rep_state: Dict[str, str] = {}
    for e in events:
        if e.get("event") == "fleet_replica_down":
            rep_state[str(e.get("replica"))] = "down"
        elif e.get("event") == "fleet_replica_regrow":
            rep_state[str(e.get("replica"))] = "up"
    replicas_down = sorted(n for n, s in rep_state.items()
                           if s == "down")
    return {"checked_ts": now, "workers": workers, "stalled": stalled,
            "dead": dead, "dead_hosts": sorted(dead_hosts),
            "numerics": numerics, "replicas_down": replicas_down,
            "healthy": not stalled and not dead and not numerics}
