"""Metrics registry — counters, gauges, and fixed-bucket histograms
with labels, exported as Prometheus text exposition (``metrics.prom``)
and a JSON snapshot (``metrics.json``).

The reference operator exposes a controller-runtime ``/metrics``
endpoint; this repo's jobs are batch processes on hosts that may have
no scrape target alive by the time anyone looks, so the exposition is
a FILE refreshed on every flush — node-exporter-textfile semantics: a
sidecar (or the operator's manager) serves or collects it, and a
finished run's numbers survive the process.

Multi-process contract: every process of a run flushes its own
snapshot under its ``proc_id`` into ``metrics.json``; the merged view
(counters/histograms summed, gauges last-write) is what
``metrics.prom`` renders. A process re-flushing REPLACES its previous
contribution (idempotent), so per-phase flushes never double-count.

Stdlib-only — imported by the control-plane image.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dgl_operator_tpu.obs._io import atomic_write, dir_lock, read_json

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# duration buckets (seconds) spanning sub-ms host ops to 10-minute
# workflow phases — the shapes this repo times
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   600.0)

# request-latency buckets (seconds) for the online serving plane:
# DEFAULT_BUCKETS is tuned for multi-second batch phases and wastes
# all its resolution above the SLO range, so serve histograms
# (serve/*, ~0.5ms–10s) use this preset — dense through the
# single-digit-millisecond band where p50/p95/p99 of a warmed request
# path actually land, with a coarse tail for cold compiles and stalls
LATENCY_BUCKETS = (0.0005, 0.001, 0.002, 0.003, 0.005, 0.0075, 0.01,
                   0.015, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)

METRICS_PROM = "metrics.prom"
METRICS_JSON = "metrics.json"


def _fmt(v: float) -> str:
    """Prometheus sample-value formatting: integral values render as
    integers (``3``, not ``3.0``); the rest use Python's shortest
    round-trip repr."""
    f = float(v)
    if math.isfinite(f) and f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class _Metric:
    kind = ""

    def __init__(self, name: str, help: str, label_names: Sequence[str],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(label_names)
        for ln in self.label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} for {name}")
        self._lock = lock
        self._samples: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name} takes labels {list(self.label_names)}, "
                f"got {sorted(labels)}")
        return tuple(str(labels[n]) for n in self.label_names)


class Counter(_Metric):
    """Monotone accumulator; ``inc`` rejects negative amounts."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc "
                             f"{amount}")
        with self._lock:
            k = self._key(labels)
            self._samples[k] = float(self._samples.get(k, 0.0)) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._samples.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._samples[self._key(labels)] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._samples[k] = float(self._samples.get(k, 0.0)) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._samples.get(self._key(labels), 0.0))


class Histogram(_Metric):
    """Fixed-bucket histogram; buckets are upper bounds (le), with an
    implicit +Inf overflow bucket. Counts are stored per-bucket and
    rendered cumulative, per the Prometheus exposition contract."""

    kind = "histogram"

    def __init__(self, name, help, label_names, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, label_names, lock)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(set(bs)) or \
                not all(math.isfinite(b) for b in bs):
            raise ValueError(f"histogram {name}: buckets must be a "
                             f"finite strictly-increasing sequence, "
                             f"got {buckets}")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        with self._lock:
            k = self._key(labels)
            s = self._samples.get(k)
            if s is None:
                s = self._samples[k] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            s["counts"][bisect.bisect_left(self.buckets, v)] += 1
            s["sum"] += v
            s["count"] += 1

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Bucket-interpolated quantile estimate (the Prometheus
        ``histogram_quantile`` rule): ``None`` with no observations.
        Consumed by ``bench_serve`` and the doctor's SLO section —
        accuracy is bounded by bucket width, so latency metrics should
        use :data:`LATENCY_BUCKETS`."""
        with self._lock:
            s = self._samples.get(self._key(labels))
            counts = list(s["counts"]) if s else []
        return quantile_from_counts(self.buckets, counts, q)


class MetricsRegistry:
    """Get-or-create metric families; name/type/label collisions raise
    loudly at creation (a silent second family would fork the data)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name, help, labels, **kw) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labels,
                                              self._lock, **kw)
                return m
        if not isinstance(m, cls):
            raise ValueError(f"metric {name} already registered as "
                             f"{m.kind}, not {cls.kind}")
        if m.label_names != tuple(labels):
            raise ValueError(
                f"metric {name} registered with labels "
                f"{list(m.label_names)}, got {list(labels)}")
        if help and not m.help:
            m.help = help
        return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """JSON-able view of every family: the exchange format flushes
        write to ``metrics.json`` and ``merge_snapshots`` consumes."""
        out: Dict[str, dict] = {}
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                fam: dict = {"type": m.kind, "help": m.help,
                             "label_names": list(m.label_names)}
                if isinstance(m, Histogram):
                    fam["buckets"] = list(m.buckets)
                samples = []
                for key, val in sorted(m._samples.items()):
                    s = {"labels": dict(zip(m.label_names, key))}
                    if isinstance(m, Histogram):
                        s.update(counts=list(val["counts"]),
                                 sum=val["sum"], count=val["count"])
                    else:
                        s["value"] = val
                    samples.append(s)
                fam["samples"] = samples
                out[name] = fam
        return out

    def to_prometheus(self) -> str:
        return render_prometheus(self.snapshot())

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


# ----------------------------------------------------------------------
def quantile_from_counts(buckets: Sequence[float],
                         counts: Sequence[int],
                         q: float) -> Optional[float]:
    """Estimate quantile ``q`` from per-bucket (non-cumulative) counts —
    the snapshot form flushed into ``metrics.json``, so the doctor can
    compute SLO quantiles from a finished run's artifacts without the
    live :class:`Histogram`. Linear interpolation inside the landing
    bucket (lower bound 0 for the first, the last finite bound for the
    +Inf overflow — a quantile landing there reports that bound, the
    honest floor). Returns ``None`` when there are no observations."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if not counts or total == 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum, cum = cum, cum + c
        if cum >= rank and c > 0:
            if i >= len(buckets):        # +Inf overflow bucket
                return float(buckets[-1])
            lo = 0.0 if i == 0 else float(buckets[i - 1])
            hi = float(buckets[i])
            frac = (rank - prev_cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    return float(buckets[-1])


def render_quantile_gauges(snapshot: Dict[str, dict],
                           families: Sequence[str] = (
                               "serve_request_seconds",
                               "serve_forward_seconds"),
                           name: str = "serve_quantile_seconds",
                           quantiles: Sequence[float] = (0.5, 0.95,
                                                         0.99)) -> str:
    """Derived p50/p95/p99 gauges rendered from histogram snapshots —
    appended to ``/metrics`` by the serving plane so scrapers without a
    ``histogram_quantile`` rule engine (curl, dashboards, the smoke
    tests) still read the SLO numbers directly. Families with no
    observations are omitted; the estimator is
    :func:`quantile_from_counts` (bucket-interpolated, same numbers
    the doctor reports)."""
    lines: List[str] = []
    for fname in families:
        fam = snapshot.get(fname)
        if not fam or fam.get("type") != "histogram" \
                or not fam.get("samples"):
            continue
        buckets = fam.get("buckets", [])
        counts = [0] * (len(buckets) + 1)
        for s in fam["samples"]:
            for i, c in enumerate(s.get("counts", [])):
                counts[i] += c
        values = [(q, quantile_from_counts(buckets, counts, q))
                  for q in quantiles]
        values = [(q, v) for q, v in values if v is not None]
        if not values:
            continue
        if not lines:
            lines.append(f"# HELP {name} bucket-interpolated latency "
                         "quantiles derived from the histogram "
                         "families")
            lines.append(f"# TYPE {name} gauge")
        for q, v in values:
            lines.append(
                f'{name}{{family="{_escape(fname)}",'
                f'quantile="{_fmt(q)}"}} {_fmt(v)}')
    return "\n".join(lines) + ("\n" if lines else "")


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Prometheus text exposition (version 0.0.4) of a snapshot."""
    lines: List[str] = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} "
                         + str(fam["help"]).replace("\\", r"\\")
                         .replace("\n", r"\n"))
        lines.append(f"# TYPE {name} {fam['type']}")
        label_names = fam.get("label_names", [])

        def pairs(labels, extra=()):
            items = [(ln, labels.get(ln, "")) for ln in label_names]
            items += list(extra)
            if not items:
                return ""
            body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
            return "{" + body + "}"

        for s in fam.get("samples", []):
            labels = s.get("labels", {})
            if fam["type"] == "histogram":
                cum = 0
                bounds = [_fmt(b) for b in fam.get("buckets", [])]
                for bound, c in zip(bounds + ["+Inf"], s["counts"]):
                    cum += c
                    lines.append(f"{name}_bucket"
                                 f"{pairs(labels, [('le', bound)])} "
                                 f"{_fmt(cum)}")
                lines.append(f"{name}_sum{pairs(labels)} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{pairs(labels)} "
                             f"{_fmt(s['count'])}")
            else:
                lines.append(f"{name}{pairs(labels)} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def _sample_key(s: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v))
                 for k, v in s.get("labels", {}).items()))


def merge_snapshots(snaps: Iterable[Dict[str, dict]]) -> Dict[str, dict]:
    """Merge per-process snapshots into one family set: counters and
    histograms sum, gauges last-write-wins. A family whose shape
    (type / labels / buckets) disagrees with an earlier process is
    replaced wholesale — telemetry merging must never raise."""
    merged: Dict[str, dict] = {}
    for snap in snaps:
        for name, fam in snap.items():
            prev = merged.get(name)
            shape = (fam.get("type"), fam.get("label_names"),
                     fam.get("buckets"))
            if prev is None or shape != (prev.get("type"),
                                         prev.get("label_names"),
                                         prev.get("buckets")):
                merged[name] = json.loads(json.dumps(fam))
                continue
            by_key = {_sample_key(s): s for s in prev["samples"]}
            for s in fam.get("samples", []):
                tgt = by_key.get(_sample_key(s))
                if tgt is None:
                    s = json.loads(json.dumps(s))
                    prev["samples"].append(s)
                    by_key[_sample_key(s)] = s
                elif fam["type"] == "counter":
                    tgt["value"] += s["value"]
                elif fam["type"] == "histogram":
                    tgt["counts"] = [a + b for a, b in
                                     zip(tgt["counts"], s["counts"])]
                    tgt["sum"] += s["sum"]
                    tgt["count"] += s["count"]
                else:  # gauge: last writer wins
                    tgt["value"] = s["value"]
            prev["samples"].sort(key=_sample_key)
    return merged


def write_files(directory: str, proc_id: str, snapshot: Dict[str, dict],
                run_id: Optional[str] = None) -> None:
    """Publish this process's snapshot into the run's shared metrics
    artifacts: ``metrics.json`` keeps every process's latest snapshot
    under ``procs`` plus the ``merged`` view; ``metrics.prom`` renders
    the merged view. The whole read-merge-write runs under the obs
    directory lock so concurrent flushes never lose an update."""
    jpath = os.path.join(directory, METRICS_JSON)
    with dir_lock(directory):
        existing = read_json(jpath, {})
        procs = dict(existing.get("procs", {}))
        procs[proc_id] = snapshot
        merged = merge_snapshots(procs[p] for p in sorted(procs))
        atomic_write(jpath, json.dumps(
            {"run": run_id or existing.get("run"),
             "procs": procs, "merged": merged},
            indent=2, sort_keys=True))
        atomic_write(os.path.join(directory, METRICS_PROM),
                     render_prometheus(merged))
