"""Live streaming metrics — rolling-window aggregates served over HTTP
while the job is still running.

The PR 4/5 obs plane is post-hoc: metrics/trace merge on flush, the
job view is collected after phase 5, and ``job_health()`` polls the
events *file*. Production serving (ROADMAP item 2) and multi-slice
debugging need the live question answered — "what is this worker's
step rate / p99 / exchange bandwidth *right now*?" — without waiting
for a flush cadence or a collection pass. This module is that layer:

- :class:`LiveFeed` — a low-overhead in-process ring buffer. Trainers
  push one cheap tick per step (:func:`~LiveFeed.tick` — a deque
  append; no locks on the reader's hot structures beyond one mutex);
  the serving plane contributes nothing per-request — rolling qps and
  windowed p50/p99 are derived on *read* by differencing registry
  snapshots (histogram bucket counts are cumulative, so a window's
  quantiles come from the bucket-count deltas between the window's
  edges via :func:`~.metrics.quantile_from_counts`).
- :class:`LiveServer` — a tiny stdlib HTTP sidecar: ``GET /livez``
  returns the rolling snapshot as JSON, ``GET /metrics`` the process
  registry's live Prometheus exposition. ``tpu-serve`` mounts the same
  payload on its main port; trainers start the sidecar when the
  launcher exports ``TPU_OPERATOR_LIVE_PORT`` (0 = ephemeral).
  Endpoints self-register under ``<obs_dir>/live/`` so ``tpu-top``
  and the controller can discover them.
- :func:`live_job_health` — the live replacement for the controller's
  file-polling stall detection: query every registered sidecar's
  ``/livez`` and judge staleness from the feed's own heartbeat ages;
  fall back to the file-based :func:`~.analyze.job_health` when no
  endpoint answers (crashed sidecars, pre-live runs). A wedged-but-
  alive trainer still answers (the sidecar thread is independent of
  the stuck loop thread), which is exactly the case file mtimes get
  wrong.

Stdlib-only — runs in the control-plane image.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from dgl_operator_tpu.obs.metrics import quantile_from_counts

LIVE_PORT_ENV = "TPU_OPERATOR_LIVE_PORT"
LIVE_SUBDIR = "live"
DEFAULT_WINDOW_S = 10.0
_LAT_FAMILY = "serve_request_seconds"


def _delta(end: float, start: float) -> float:
    """Cumulative-counter delta that survives a reset (PhaseTimer
    resets per epoch): a value that went DOWN restarted from 0, so the
    honest window delta is the end value."""
    d = end - start
    return d if d >= 0 else end


class LiveFeed:
    """Per-process rolling-window aggregator. Writers call
    :meth:`tick` once per step (trainers) — the serving side needs no
    writer at all; :meth:`snapshot` derives the window's rates on
    demand. Thread-safe; ``clock`` injectable for tests."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 maxlen: int = 4096,
                 clock: Callable[[], float] = time.time):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        # (ts, step, exchange_bytes, stall_s, busy_s, mfu, hbm_mib,
        # overlap_ratio, loss, grad_norm, comm_bytes, phase_totals)
        # per heartbeat (comm_bytes: cumulative per-mesh-axis dict
        # from the comm watcher, obs/comm.axis_bytes_total — or None;
        # phase_totals: the PhaseTimer's cumulative per-bucket seconds,
        # differenced into the rolling critpath_frac — or None)
        self._ticks: deque = deque(maxlen=maxlen)
        # (ts, requests, shed, lat_counts) registry extracts, ringed so
        # successive reads can difference against the window's far edge
        self._reg: deque = deque(maxlen=256)
        self._lat_buckets: Tuple[float, ...] = ()
        self._done = False

    # -- writers -------------------------------------------------------
    def tick(self, step: int, timer=None,
             ts: Optional[float] = None,
             mfu: Optional[float] = None,
             hbm_mib: Optional[float] = None,
             overlap_ratio: Optional[float] = None,
             loss: Optional[float] = None,
             grad_norm: Optional[float] = None,
             comm_bytes: Optional[Dict[str, float]] = None) -> None:
        """One training heartbeat: global step plus (optionally) the
        trainer's PhaseTimer snapshot, from which the window derives
        exchange MiB/s and the stall fraction, plus the profiler's
        rolling MFU and HBM watermark (obs/prof.py) when utilization
        accounting is configured, plus the pipelined trainer's rolling
        hidden-exchange fraction (``overlap_ratio``,
        runtime/timers.OverlapTracker) — surfaced live next to ``mfu``
        on /livez and in tpu-top instead of waiting for the epoch
        record. ``loss`` / ``grad_norm`` are the model-health plane's
        riders (obs/quality.py — the sentry's one-step-delayed host
        fetch), surfaced as the /livez ``loss``/``grad_norm`` keys and
        the tpu-top ``loss``/``gnorm`` columns. ``comm_bytes`` is the
        comm watcher's cumulative per-mesh-axis byte dict
        (obs/comm.axis_bytes_total) — the window difference becomes
        the /livez ``comm_mib_per_s`` rate and the tpu-top
        ``comMiB/s`` column."""
        snap = timer.snapshot() if timer is not None else {}
        total = snap.get("total", {})
        busy = (total.get("stall", 0.0) + total.get("sample", 0.0)
                + total.get("dispatch", 0.0))
        rec = (self._clock() if ts is None else ts, int(step),
               float(snap.get("bytes", {}).get("exchange", 0)),
               float(total.get("stall", 0.0)), float(busy),
               (None if mfu is None else float(mfu)),
               (None if hbm_mib is None else float(hbm_mib)),
               (None if overlap_ratio is None
                else float(overlap_ratio)),
               (None if loss is None else float(loss)),
               (None if grad_norm is None else float(grad_norm)),
               (None if comm_bytes is None
                else {str(k): float(v)
                      for k, v in comm_bytes.items()}),
               (None if timer is None
                else {str(k): float(v) for k, v in total.items()}))
        with self._lock:
            self._ticks.append(rec)

    def mark_done(self) -> None:
        """Terminal marker (the live twin of the ``train_done`` event):
        silence after this is completion, not a stall."""
        with self._lock:
            self._done = True

    def reset(self) -> None:
        with self._lock:
            self._ticks.clear()
            self._reg.clear()
            self._done = False

    # -- registry extraction (serve side) ------------------------------
    @staticmethod
    def _extract(reg_snapshot: Dict[str, dict]):
        def counter(name: str) -> float:
            fam = reg_snapshot.get(name) or {}
            return float(sum(s.get("value", 0)
                             for s in fam.get("samples", [])))

        fam = reg_snapshot.get(_LAT_FAMILY) or {}
        buckets = tuple(fam.get("buckets") or ())
        counts = [0] * (len(buckets) + 1)
        for s in fam.get("samples", []):
            for i, c in enumerate(s.get("counts", [])):
                counts[i] += c
        return (counter("serve_requests_total"),
                counter("serve_requests_shed_total"), buckets, counts)

    # -- reader --------------------------------------------------------
    def snapshot(self, registry=None,
                 window_s: Optional[float] = None) -> Dict:
        """The rolling-window aggregate: training-side rates from the
        tick ring, serving-side qps/quantiles from registry-snapshot
        deltas. Keys are ``None`` when the window holds no signal yet
        (an idle feed never reports a bogus 0 rate)."""
        w = float(window_s or self.window_s)
        now = self._clock()
        out: Dict = {"ts": round(now, 3), "window_s": w}
        with self._lock:
            ticks = [t for t in self._ticks if t[0] >= now - w]
            if not ticks and self._ticks:
                ticks = [self._ticks[-1]]
            done = self._done
        out["done"] = done
        out.update(self._tick_stats(ticks, now))
        if registry is not None:
            out.update(self._serve_stats(registry.snapshot(), now, w))
        return out

    @staticmethod
    def _tick_stats(ticks: List[tuple], now: float) -> Dict:
        out: Dict = {"step": None, "step_rate_hz": None,
                     "heartbeat_hz": None, "last_heartbeat_ts": None,
                     "median_interval_s": None,
                     "exchange_mib_per_s": None, "stall_frac": None,
                     "mfu": None, "hbm_mib": None,
                     "overlap_ratio": None, "loss": None,
                     "grad_norm": None, "comm_mib_per_s": None,
                     "comm_axis_mib_per_s": None,
                     "critpath_frac": None}
        if not ticks:
            return out
        out["step"] = ticks[-1][1]
        out["last_heartbeat_ts"] = round(ticks[-1][0], 6)
        # profiler/pipeline/model-health riders: last tick in the
        # window that carried each (obs/prof.py mfu+hbm; the trainer's
        # rolling hidden-exchange fraction; the quality plane's
        # loss/grad norm)
        riders = (("mfu", 5, 4), ("hbm_mib", 6, 1),
                  ("overlap_ratio", 7, 4), ("loss", 8, 6),
                  ("grad_norm", 9, 6))
        for t in reversed(ticks):
            for key, idx, nd in riders:
                if out[key] is None and t[idx] is not None:
                    out[key] = round(t[idx], nd)
            if all(out[key] is not None for key, _, _ in riders):
                break
        if len(ticks) < 2:
            return out
        dt = ticks[-1][0] - ticks[0][0]
        gaps = [b[0] - a[0] for a, b in zip(ticks, ticks[1:])]
        out["median_interval_s"] = round(
            max(statistics.median(gaps), 1e-6), 6)
        if dt <= 0:
            return out
        out["step_rate_hz"] = round((ticks[-1][1] - ticks[0][1]) / dt, 4)
        out["heartbeat_hz"] = round((len(ticks) - 1) / dt, 4)
        out["exchange_mib_per_s"] = round(
            _delta(ticks[-1][2], ticks[0][2]) / 2**20 / dt, 4)
        busy = _delta(ticks[-1][4], ticks[0][4])
        if busy > 0:
            out["stall_frac"] = round(
                _delta(ticks[-1][3], ticks[0][3]) / busy, 4)
        # per-axis collective rate: window delta of the comm watcher's
        # cumulative byte dict (first/last ticks in the window that
        # carried one; the dict is cumulative, so _delta survives
        # process restarts like the exchange counter above)
        carried = [t for t in ticks if t[10] is not None]
        if len(carried) >= 2:
            first, last = carried[0], carried[-1]
            cdt = last[0] - first[0]
            if cdt > 0:
                axes = {
                    ax: round(_delta(last[10].get(ax, 0.0),
                                     first[10].get(ax, 0.0))
                              / 2**20 / cdt, 4)
                    for ax in last[10]}
                out["comm_axis_mib_per_s"] = axes
                out["comm_mib_per_s"] = round(sum(axes.values()), 4)
        # rolling critical-path attribution (ISSUE 20): window delta
        # of the timer's cumulative phase buckets, normalized into
        # category fractions by the xray's phase→category mapping —
        # the live single-worker estimate of critpath_frac{category}
        timed = [t for t in ticks if len(t) > 11 and t[11] is not None]
        if len(timed) >= 2:
            from dgl_operator_tpu.obs.xray import live_critpath
            first, last = timed[0], timed[-1]
            deltas = {ph: _delta(last[11].get(ph, 0.0),
                                 first[11].get(ph, 0.0))
                      for ph in last[11]}
            out["critpath_frac"] = live_critpath(deltas)
        return out

    def _serve_stats(self, reg_snapshot, now: float, w: float) -> Dict:
        cur = self._extract(reg_snapshot)
        with self._lock:
            base = None
            for rec in self._reg:
                if rec[0] <= now - w:
                    base = rec
                else:
                    break
            if base is None and self._reg:
                base = self._reg[0]
            if base is not None and (
                    cur[0] < base[1]
                    or (len(base[4]) == len(cur[3])
                        and any(a < b
                                for a, b in zip(cur[3], base[4])))):
                # registry reset (engine restart / checkpoint
                # promotion re-registered the serve histograms): every
                # pre-reset record describes a dead incarnation, and
                # differencing against one yields negative qps and
                # zeroed quantile windows. Restart the window at the
                # new incarnation instead — one snapshot of warm-up
                # (Nones, like process start) beats lying.
                self._reg.clear()
                base = None
            self._reg.append((now, *cur))
            self._lat_buckets = cur[2] or self._lat_buckets
        out: Dict = {"qps": None, "p50_ms": None, "p95_ms": None,
                     "p99_ms": None,
                     "requests_total": int(cur[0]),
                     "shed_total": int(cur[1])}
        if base is None:
            return out
        dt = now - base[0]
        if dt <= 0:
            return out
        out["qps"] = round(_delta(cur[0], base[1]) / dt, 3)
        # windowed quantiles: bucket-count deltas between the window's
        # edges (cumulative per-bucket counts difference cleanly; a
        # bucket layout that appeared mid-window falls back to all-time)
        if len(base[4]) == len(cur[3]):
            counts = [max(a - b, 0) for a, b in zip(cur[3], base[4])]
        else:
            counts = cur[3]
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"),
                       (0.99, "p99_ms")):
            v = quantile_from_counts(cur[2], counts, q)
            out[key] = round(v * 1e3, 3) if v is not None else None
        return out


# ------------------------------------------------------- process feed
_feed: Optional[LiveFeed] = None
_feed_lock = threading.Lock()


def get_feed() -> LiveFeed:
    """The process-global feed (trainers tick it; sidecars read it)."""
    global _feed
    with _feed_lock:
        if _feed is None:
            _feed = LiveFeed()
        return _feed


def reset_feed() -> None:
    """Fresh feed (tests; a driver starting a second logical run)."""
    global _feed
    with _feed_lock:
        _feed = None


# --------------------------------------------------------- the sidecar
class _LiveHandler(BaseHTTPRequestHandler):
    server_version = "tpu-livez/0.1"

    def log_message(self, fmt, *args):  # liveness polls are not news
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/livez":
            self._reply(200, json.dumps(self.server.live.payload())
                        .encode(), "application/json")
        elif self.path == "/metrics":
            from dgl_operator_tpu.obs import get_obs
            self._reply(200, get_obs().metrics.to_prometheus().encode(),
                        "text/plain; version=0.0.4")
        else:
            self._reply(404, json.dumps(
                {"error": f"unknown path {self.path}"}).encode(),
                "application/json")


class LiveServer:
    """The trainer-side live sidecar: /livez + /metrics on a loopback
    port, self-registered under ``<obs_dir>/live/`` for discovery.
    ``extra`` is a zero-arg callable merged into the payload (the
    serving plane adds SLO state and shed status this way)."""

    def __init__(self, feed: Optional[LiveFeed] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 role: Optional[str] = None,
                 with_registry: bool = True,
                 extra: Optional[Callable[[], Dict]] = None):
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        self.feed = feed if feed is not None else get_feed()
        self.role = role or obs.role
        self.with_registry = with_registry
        self.extra = extra
        self.httpd = ThreadingHTTPServer((host, port), _LiveHandler)
        self.httpd.live = self
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._endpoint_path: Optional[str] = None

    def payload(self) -> Dict:
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        registry = obs.metrics if self.with_registry else None
        out = self.feed.snapshot(registry=registry)
        out.update(host=obs.host, pid=obs.pid, role=self.role,
                   port=self.port)
        if self.extra is not None:
            try:
                out.update(self.extra() or {})
            except Exception:  # noqa: BLE001 — liveness must not 500
                pass
        return out

    def start(self) -> "LiveServer":
        from dgl_operator_tpu.obs import get_obs
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="tpu-livez", daemon=True)
        self._thread.start()
        self._endpoint_path = register_endpoint(self.port, self.role)
        get_obs().events.emit("live_listening", port=self.port,
                              role=self.role)
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._endpoint_path:
            try:
                os.remove(self._endpoint_path)
            except OSError:
                pass
            self._endpoint_path = None


# ---------------------------------------------- discovery + health
def _live_dir(obs_dir: str) -> str:
    return os.path.join(obs_dir, LIVE_SUBDIR)


def register_endpoint(port: int, role: str,
                      obs_dir: Optional[str] = None) -> Optional[str]:
    """Drop this process's live endpoint into the run's discovery
    directory (``<obs_dir>/live/``). Best-effort: a read-only obs dir
    costs the run discovery, never the job."""
    from dgl_operator_tpu.obs import get_obs
    obs = get_obs()
    obs_dir = obs_dir or obs.directory
    if not obs_dir:
        return None
    try:
        d = _live_dir(obs_dir)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"{obs.host}-{obs.pid}-{role}.json".replace("/", "_"))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": obs.host, "pid": obs.pid, "role": role,
                       "addr": "127.0.0.1", "port": int(port),
                       "ts": round(time.time(), 3)}, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def live_endpoints(obs_dir: str) -> List[Dict]:
    """Registered live endpoints of a run, oldest first."""
    d = _live_dir(obs_dir)
    out: List[Dict] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                ep = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(ep, dict) and ep.get("port"):
            out.append(ep)
    return out


def fetch_livez(ep: Dict, timeout: float = 1.0) -> Optional[Dict]:
    """One endpoint's /livez snapshot, or ``None`` (dead process,
    recycled port) — callers treat unreachable as 'fall back'."""
    url = f"http://{ep.get('addr', '127.0.0.1')}:{ep['port']}/livez"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            snap = json.load(r)
        return snap if isinstance(snap, dict) else None
    except (OSError, ValueError):
        return None


def live_job_health(obs_dir: str, now: Optional[float] = None,
                    stall_factor: Optional[float] = None,
                    stall_grace_s: Optional[float] = None,
                    timeout: float = 1.0) -> Dict:
    """Job health from the live feeds, file fallback. Same shape as
    :func:`~.analyze.job_health` plus ``source``: ``"live"`` when at
    least one sidecar answered (each answering worker judged from its
    feed's own heartbeat ages — a wedged loop thread cannot stop the
    sidecar from truthfully reporting the growing silence), ``"file"``
    when none did (the PR 5 path, byte-for-byte)."""
    from dgl_operator_tpu.obs.analyze import (DEFAULT_STALL_FACTOR,
                                              DEFAULT_STALL_GRACE_S,
                                              job_health)
    stall_factor = (DEFAULT_STALL_FACTOR if stall_factor is None
                    else stall_factor)
    stall_grace_s = (DEFAULT_STALL_GRACE_S if stall_grace_s is None
                     else stall_grace_s)
    snaps = [(ep, fetch_livez(ep, timeout=timeout))
             for ep in live_endpoints(obs_dir)]
    live = [(ep, s) for ep, s in snaps if s]
    if not live:
        out = job_health(obs_dir, now=now, stall_factor=stall_factor,
                         stall_grace_s=stall_grace_s)
        out["source"] = "file"
        return out
    now = time.time() if now is None else now
    workers: Dict[str, Dict] = {}
    stalled: List[str] = []
    for ep, s in live:
        w = f"{s.get('host', ep.get('host', '?'))}:" \
            f"{s.get('pid', ep.get('pid', '?'))}:" \
            f"{s.get('role', ep.get('role', '?'))}"
        last = s.get("last_heartbeat_ts")
        if last is None:
            continue   # serving/driver feeds carry no heartbeat
        med = s.get("median_interval_s") or stall_grace_s
        window = max(stall_factor * med, stall_grace_s)
        silent = max(now - float(last), 0.0)
        if s.get("done"):
            status = "done"
        elif silent > window:
            status = "stalled"
            stalled.append(w)
        else:
            status = "ok"
        workers[w] = {"status": status, "last_step": s.get("step"),
                      "last_heartbeat_ts": last,
                      "silent_s": round(silent, 3),
                      "stall_window_s": round(window, 3),
                      "terminal": ({"event": "train_done"}
                                   if s.get("done") else None)}
    # dead workers (host_died — the elastic shrink trigger) and
    # numerics-faulted workers (the sentry halted them, obs/quality.py)
    # can only come from the FILE plane: a dead host's sidecar is gone
    # with the process and a halted trainer's sidecar stops with it,
    # so the live view alone would misread permanent loss as mere
    # silence. Merge the events-file verdict in.
    dead: List[str] = []
    dead_hosts: List[str] = []
    numerics: List[str] = []
    try:
        fsnap = job_health(obs_dir, now=now, stall_factor=stall_factor,
                           stall_grace_s=stall_grace_s)
        dead = list(fsnap.get("dead") or [])
        dead_hosts = list(fsnap.get("dead_hosts") or [])
        numerics = list(fsnap.get("numerics") or [])
        for w in dead:
            workers.setdefault(w, fsnap["workers"].get(w) or
                               {"status": "dead"})
            workers[w]["status"] = "dead"
        for w in numerics:
            workers.setdefault(w, fsnap["workers"].get(w) or
                               {"status": "numerics_fault"})
            workers[w]["status"] = "numerics_fault"
        stalled = [w for w in stalled
                   if w not in dead and w not in numerics]
    except Exception:  # noqa: BLE001 — the live view stands alone
        pass
    return {"checked_ts": now, "workers": workers, "stalled": stalled,
            "dead": dead, "dead_hosts": dead_hosts,
            "numerics": numerics,
            "healthy": not stalled and not dead and not numerics,
            "source": "live"}


# -------------------------------------------------- env-gated startup
_sidecar: Optional[LiveServer] = None
_sidecar_lock = threading.Lock()


def maybe_start_sidecar(role: Optional[str] = None
                        ) -> Optional[LiveServer]:
    """Start the trainer live sidecar when the launcher asked for one
    (``TPU_OPERATOR_LIVE_PORT`` exported; ``0`` = ephemeral port).
    Idempotent per process; never raises — a port collision costs the
    run its live feed, not the training."""
    global _sidecar
    port_env = os.environ.get(LIVE_PORT_ENV)
    if port_env is None or port_env == "":
        return None
    with _sidecar_lock:
        if _sidecar is not None:
            return _sidecar
        try:
            _sidecar = LiveServer(port=int(port_env),
                                  role=role).start()
        except (OSError, ValueError) as exc:
            print(f"obs: live sidecar failed to start ({exc}); "
                  "continuing without a live feed", flush=True)
            return None
        return _sidecar


def stop_sidecar() -> None:
    """Tear the env-gated sidecar down (tests; process teardown is
    otherwise covered by daemon threads)."""
    global _sidecar
    with _sidecar_lock:
        sc, _sidecar = _sidecar, None
    if sc is not None:
        sc.stop()
