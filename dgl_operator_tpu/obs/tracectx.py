"""Trace-context propagation — one request or one training step as a
single contiguous span tree across threads, processes, and hosts.

PR 4's :class:`~.trace.Tracer` gives every process its own span track,
but nothing LINKS the driver's ``phase 5`` span to the trainer spans it
spawned, or an HTTP request's server span to the batch that eventually
executed it — the merged ``trace.json`` is a pile of parallel tracks.
This module carries a W3C-traceparent-shaped context through the two
boundaries this repo actually has:

- **process boundary** (driver → worker subprocess): the active span
  exports ``TPU_OPERATOR_TRACE_ID`` / ``TPU_OPERATOR_TRACE_PARENT``
  into the environment (the same pattern ``TPU_OPERATOR_OBS_ROLE``
  rides), every fabric implementation forwards the environment, and a
  child process with no local context roots its spans under the
  exported parent via :func:`current`;
- **thread boundary** (HTTP handler → batcher thread → engine): the
  context is an explicit value (``current()`` → carry → :func:`use`),
  never implicit thread-local inheritance, so the threaded batcher
  cannot leak one request's context into a concurrent one.

Span records gain ``args.trace_id`` / ``args.span_id`` /
``args.parent_id`` (stamped by :class:`~.trace.Tracer` for every span
recorded while a context is active), so Perfetto queries and the tests
can reassemble the tree from the merged job trace.

Stdlib-only — imported by the control-plane image.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

TRACE_ID_ENV = "TPU_OPERATOR_TRACE_ID"
TRACE_PARENT_ENV = "TPU_OPERATOR_TRACE_PARENT"
# HTTP carrier (serve path): "trace_id-span_id", the env pair as one
# header value
TRACE_HEADER = "X-Tpu-Trace"


def _gen_id(nbytes: int = 8) -> str:
    return uuid.uuid4().hex[: nbytes * 2]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One span's identity: which trace it belongs to, its own id, and
    the span it hangs under (``None`` for a trace root)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _gen_id(), self.span_id)

    # -- carriers -----------------------------------------------------
    def header(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    @classmethod
    def from_header(cls, value: Optional[str]
                    ) -> Optional["TraceContext"]:
        if not value:
            return None
        parts = value.strip().split("-")
        if len(parts) != 2 or not all(parts):
            return None
        return cls(trace_id=parts[0], span_id=parts[1])

    def env(self) -> Dict[str, str]:
        """The env pair a child process re-roots under — children of
        this span become children of ``span_id``."""
        return {TRACE_ID_ENV: self.trace_id,
                TRACE_PARENT_ENV: self.span_id}

    def ids(self) -> Dict[str, str]:
        """Span-record args (``parent_id`` omitted for roots)."""
        out = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            out["parent_id"] = self.parent_id
        return out


def new_root() -> TraceContext:
    return TraceContext(trace_id=_gen_id(16), span_id=_gen_id())


def from_env(environ=None) -> Optional[TraceContext]:
    """The context a parent process exported, or ``None``. The returned
    context IS the remote parent span — local spans created under it
    become its children in the merged trace."""
    environ = os.environ if environ is None else environ
    tid = environ.get(TRACE_ID_ENV)
    if not tid:
        return None
    return TraceContext(trace_id=tid,
                        span_id=environ.get(TRACE_PARENT_ENV) or tid)


_tls = threading.local()


def _stack(self=_tls) -> list:
    st = getattr(self, "stack", None)
    if st is None:
        st = self.stack = []
    return st


def current() -> Optional[TraceContext]:
    """The active context: this thread's innermost :func:`span` /
    :func:`use`, else the context the parent process exported, else
    ``None`` (tracing is strictly opt-in — uninstrumented paths pay
    one env lookup)."""
    st = _stack()
    if st:
        return st[-1]
    return from_env()


def current_ids() -> Dict[str, str]:
    """Stamp-ready args of the active context ({} when none) — what
    :class:`~.trace.Tracer` merges into every span record."""
    ctx = current()
    return ctx.ids() if ctx is not None else {}


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Activate an explicitly-carried context on THIS thread (the
    batcher activating a request's context before driving the engine).
    ``None`` passes through as a no-op so carriers never need a
    conditional."""
    if ctx is None:
        yield None
        return
    st = _stack()
    st.append(ctx)
    try:
        yield ctx
    finally:
        st.pop()


@contextlib.contextmanager
def span(name: str, cat: str = "trace", export_env: bool = False,
         ctx: Optional[TraceContext] = None,
         **args) -> Iterator[TraceContext]:
    """Open a child span of the active (or given) context — or a fresh
    trace root when there is none — record it as a complete trace event
    on exit, and keep it active for the block so nested spans and
    :func:`current_ids` stamps attach under it.

    ``export_env=True`` additionally publishes the span into the
    process environment for the duration of the block, so subprocesses
    the fabric spawns inside it (phase entry points, trainers) root
    their spans under this one — the driver→worker propagation leg.
    """
    parent = ctx if ctx is not None else current()
    me = parent.child() if parent is not None else new_root()
    st = _stack()
    st.append(me)
    prev_env = None
    if export_env:
        prev_env = {k: os.environ.get(k) for k in (TRACE_ID_ENV,
                                                   TRACE_PARENT_ENV)}
        os.environ.update(me.env())
    t0 = time.perf_counter()
    try:
        yield me
    finally:
        t1 = time.perf_counter()
        st.pop()
        if prev_env is not None:
            for k, v in prev_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        from dgl_operator_tpu.obs import get_obs
        get_obs().tracer.complete(name, t0, t1, cat=cat, **me.ids(),
                                  **args)


def env_of_current() -> Dict[str, str]:
    """The env pair of the active context ({} when none) — what
    ``launch_train`` folds into every worker's environment next to
    ``TPU_OPERATOR_OBS_ROLE``."""
    ctx = current()
    return ctx.env() if ctx is not None else {}
