"""Crash-safe flight recorder — the last N seconds of comm / step /
heartbeat samples per process, dumped on the way down.

The obs plane's post-mortem story (PR 5 doctor, PR 11 live feeds) reads
what a process *flushed*; a chaos ``host:die`` exits through
``os._exit`` and a SIGTERM kill may land mid-collective, so the most
interesting window — what was in flight when the process died — never
reaches ``metrics.prom``. This module is the black box for that window:

- :class:`FlightRecorder` — a bounded ring (time window + sample cap)
  of ``(ts, kind, payload)`` samples. Writers are the comm watcher
  (``kind="comm"``, start/done phases per watched collective window,
  obs/comm.py) and the trainer heartbeat (``kind="heartbeat"``,
  runtime/loop.py). A ``note()`` is one deque append under a mutex —
  cheap enough for the hot loop.
- :meth:`FlightRecorder.dump` — atomic best-effort write of the ring
  to ``<obs_dir>/flight-<pid>.json`` with the dump reason and the LAST
  COLLECTIVE IN FLIGHT (the newest ``comm`` start with no matching
  done). Called explicitly by the chaos death path
  (``PreemptionGuard._die`` — ``os._exit`` runs no handlers, so the
  dump must precede it) and the preemption path
  (``runtime/loop.flush_and_preempt``), and wired to SIGTERM +
  ``sys.excepthook`` by :meth:`FlightRecorder.install` for processes
  that die without either.
- :func:`load_flights` — every ``flight-*.json`` of a run, merged by
  ``tpu-doctor`` into an incident timeline naming the collective that
  was in flight when each process died (obs/doctor.py).

Stdlib-only; never raises into the caller — a failed dump costs the
post-mortem, not the exit path.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

FLIGHT_PREFIX = "flight-"
DEFAULT_WINDOW_S = 30.0
DEFAULT_MAXLEN = 2048


class FlightRecorder:
    """Per-process bounded sample ring. Thread-safe; ``clock``
    injectable for tests. The ring bounds BOTH ways: at most ``maxlen``
    samples, and :meth:`samples` returns only the trailing
    ``window_s`` seconds."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 maxlen: int = DEFAULT_MAXLEN,
                 clock: Callable[[], float] = time.time):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(maxlen))
        self._installed = False

    # -- writers -------------------------------------------------------
    def note(self, kind: str, **payload) -> None:
        """Append one sample (one locked deque append; never raises)."""
        try:
            rec = {"ts": round(self._clock(), 6), "kind": str(kind),
                   **payload}
            with self._lock:
                self._ring.append(rec)
        except Exception:  # noqa: BLE001 — telemetry never raises
            pass

    # -- readers -------------------------------------------------------
    def samples(self) -> List[Dict]:
        """The trailing-window samples, oldest first."""
        now = self._clock()
        with self._lock:
            recs = list(self._ring)
        return [dict(r) for r in recs
                if r.get("ts", 0.0) >= now - self.window_s]

    def last_comm_inflight(self) -> Optional[Dict]:
        """The newest ``comm`` start sample with no matching done —
        the collective that was in flight when the ring stopped, or
        ``None`` (nothing in flight / no comm samples at all)."""
        done = set()
        with self._lock:
            recs = list(self._ring)
        for r in reversed(recs):
            if r.get("kind") != "comm":
                continue
            if r.get("phase") == "done":
                done.add(r.get("seq"))
            elif r.get("phase") == "start" and r.get("seq") not in done:
                return dict(r)
        return None

    def last_comm(self) -> Optional[Dict]:
        """The newest ``comm`` start sample, in flight or not — the
        incident timeline's fallback when the process died BETWEEN
        collectives (the watcher closed the window microseconds before
        the kill landed): naming the last collective is still the
        honest answer to "what was the network doing"."""
        with self._lock:
            recs = list(self._ring)
        for r in reversed(recs):
            if r.get("kind") == "comm" and r.get("phase") == "start":
                return dict(r)
        return None

    # -- the dump ------------------------------------------------------
    def dump(self, reason: str,
             obs_dir: Optional[str] = None) -> Optional[str]:
        """Atomic write of the ring to ``<obs_dir>/flight-<pid>.json``.
        Best-effort: returns the path, or ``None`` when there is no obs
        dir / the write failed — the exit path must proceed either
        way."""
        try:
            from dgl_operator_tpu.obs import get_obs
            obs = get_obs()
            obs_dir = obs_dir or obs.directory
            if not obs_dir:
                return None
            payload = {
                "pid": os.getpid(), "host": obs.host, "role": obs.role,
                "reason": str(reason),
                "ts": round(self._clock(), 3),
                "window_s": self.window_s,
                "inflight": self.last_comm_inflight(),
                "last_comm": self.last_comm(),
                "samples": self.samples(),
            }
            os.makedirs(obs_dir, exist_ok=True)
            path = os.path.join(obs_dir,
                                f"{FLIGHT_PREFIX}{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — a failed dump never raises
            return None

    # -- fault hooks ---------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Chain the dump into SIGTERM and ``sys.excepthook`` so an
        external kill or an unhandled fault leaves the black box.
        Signal chaining preserves whatever handler was there (the
        trainer's preemption flag-setter keeps working); main-thread
        only (CPython restriction), idempotent, best-effort."""
        if self._installed:
            return self
        prev_hook = sys.excepthook

        def _hook(etype, value, tb):
            self.dump("fault")
            prev_hook(etype, value, tb)

        sys.excepthook = _hook
        if threading.current_thread() is threading.main_thread():
            try:
                prev = signal.getsignal(signal.SIGTERM)

                def _on_term(signum, frame):
                    self.dump("sigterm")
                    if callable(prev):
                        prev(signum, frame)
                    elif prev == signal.SIG_DFL:
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

                signal.signal(signal.SIGTERM, _on_term)
            except (ValueError, OSError):
                pass
        self._installed = True
        return self


# ------------------------------------------------- process recorder
_flight: Optional[FlightRecorder] = None
_flight_lock = threading.Lock()


def get_flight() -> FlightRecorder:
    """The process-global recorder (the comm watcher and the heartbeat
    note into it; the death paths dump it)."""
    global _flight
    with _flight_lock:
        if _flight is None:
            _flight = FlightRecorder()
        return _flight


def reset_flight() -> None:
    """Fresh recorder (tests; a driver starting a second run)."""
    global _flight
    with _flight_lock:
        _flight = None


# ---------------------------------------------------- doctor's reader
def load_flights(obs_dir: str) -> List[Dict]:
    """Every process's flight dump of a run, sorted by dump time —
    what ``tpu-doctor`` merges into the incident timeline."""
    out: List[Dict] = []
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(FLIGHT_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(obs_dir, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            out.append(rec)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out
