"""Rolling-window SLO evaluation with burn-rate hysteresis.

A single slow request must not flip the serving plane into shedding,
and one fast one must not flip it back — that thrash is worse than
either steady state. The monitor therefore evaluates each target over
a rolling window of observations and acts on the *burn rate* (the
fraction of the window's evaluations in breach): breach state engages
when the burn rate crosses ``burn_threshold`` and releases when it
drops back below — classic multi-sample SLO burn alerting, scaled down
to one process.

Targets come from the knob registry (``autotune/knobs.py`` layer
``slo`` — ``slo_p99_ms``, ``slo_min_heartbeat_hz``, ``slo_window_s``),
so operators tune SLOs through the same declarations, validation, and
``tuned.json`` manifest path as every other knob.

Consumers: the serve plane feeds :meth:`SLOMonitor.evaluate` with
:meth:`~.live.LiveFeed.snapshot` payloads and routes the verdict into
the micro-batcher's shed switch (``serve/server.py``); the breach and
recovery edges land in the event log (``slo_breach`` /
``slo_recovered``) where ``tpu-doctor``'s analytics pick them up.

Stdlib-only — runs in the control-plane image.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

DEFAULT_BURN_THRESHOLD = 0.5
_SLO_KNOB_PREFIX = "slo_"
# knobs that configure the monitor itself rather than naming a target
_NON_TARGET_KNOBS = ("slo_window_s",)


def default_targets() -> Dict[str, float]:
    """Target thresholds from the knob registry's ``slo`` layer,
    keyed without the ``slo_`` prefix (``p99_ms``,
    ``min_heartbeat_hz``)."""
    from dgl_operator_tpu.autotune.knobs import REGISTRY
    return {name[len(_SLO_KNOB_PREFIX):]: k.default
            for name, k in REGISTRY.items()
            if k.layer == "slo" and name not in _NON_TARGET_KNOBS}


def default_window_s() -> float:
    from dgl_operator_tpu.autotune.knobs import default_of
    return float(default_of("slo_window_s"))


class SLOMonitor:
    """Evaluate live snapshots against SLO targets; report the set of
    currently-breaching targets and emit edge telemetry.

    Supported targets (absent snapshot signals are skipped — a
    training-only feed never breaches the serving SLO):

    - ``p99_ms``: breach when the window's p99 request latency exceeds
      the ceiling;
    - ``min_heartbeat_hz``: breach when the heartbeat rate falls below
      the floor (the live twin of the stall analytics).
    """

    def __init__(self, targets: Optional[Dict[str, float]] = None,
                 window_s: Optional[float] = None,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 clock: Callable[[], float] = time.time):
        self.targets = (dict(targets) if targets is not None
                        else default_targets())
        self.window_s = float(window_s if window_s is not None
                              else default_window_s())
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._evals: Dict[str, Deque[Tuple[float, bool]]] = {}
        self._breaching: Dict[str, bool] = {}

    # -- target checks -------------------------------------------------
    def _checks(self, snap: Dict) -> List[Tuple[str, float, float, bool]]:
        out: List[Tuple[str, float, float, bool]] = []
        t = self.targets
        p99 = snap.get("p99_ms")
        if t.get("p99_ms") is not None and p99 is not None:
            out.append(("p99_ms", float(p99), float(t["p99_ms"]),
                        float(p99) > float(t["p99_ms"])))
        hz = snap.get("heartbeat_hz")
        if t.get("min_heartbeat_hz") and hz is not None \
                and not snap.get("done"):
            out.append(("min_heartbeat_hz", float(hz),
                        float(t["min_heartbeat_hz"]),
                        float(hz) < float(t["min_heartbeat_hz"])))
        return out

    # -- evaluation ----------------------------------------------------
    def evaluate(self, snap: Dict) -> List[Dict]:
        """Fold one live snapshot into the rolling windows; returns the
        currently-breaching targets (empty = all SLOs met). Breach and
        recovery EDGES are evented and counted; the per-target burn
        rate is exported as the ``slo_burn_rate`` gauge."""
        from dgl_operator_tpu.obs import get_obs
        obs = get_obs()
        now = self._clock()
        breaches: List[Dict] = []
        for name, value, threshold, bad in self._checks(snap):
            dq = self._evals.setdefault(name, deque())
            dq.append((now, bad))
            while dq and dq[0][0] < now - self.window_s:
                dq.popleft()
            burn = sum(1 for _, b in dq if b) / len(dq)
            breaching = burn >= self.burn_threshold
            obs.metrics.gauge(
                "slo_burn_rate",
                "fraction of the rolling window in breach per target",
                labels=("target",)).set(burn, target=name)
            prev = self._breaching.get(name, False)
            if breaching and not prev:
                obs.metrics.counter(
                    "slo_breaches_total",
                    "SLO targets that entered breach state",
                    labels=("target",)).inc(target=name)
                obs.events.emit("slo_breach", target=name,
                                value=round(value, 4),
                                threshold=threshold,
                                burn_rate=round(burn, 3))
            elif prev and not breaching:
                obs.events.emit("slo_recovered", target=name,
                                value=round(value, 4),
                                threshold=threshold,
                                burn_rate=round(burn, 3))
            self._breaching[name] = breaching
            if breaching:
                breaches.append({"target": name,
                                 "value": round(value, 4),
                                 "threshold": threshold,
                                 "burn_rate": round(burn, 3)})
        return breaches

    def state(self) -> Dict:
        """Current verdict for /livez and tpu-top: overall ok plus the
        breaching-target list."""
        breaching = sorted(n for n, b in self._breaching.items() if b)
        return {"ok": not breaching, "breaching": breaching,
                "targets": dict(self.targets)}
