"""Unified telemetry layer: structured events, a metrics registry with
Prometheus exposition, and trace spans — one ``obs/`` directory per
run, shared by every process of that run.

The reference's only instrumentation is ``date +%s`` deltas printed by
the bash drivers plus ad-hoc per-step buckets in the training loop;
both die with the process. This package gives every layer (launcher →
controller → training loop) one surface that SURVIVES the run:

- :class:`~.events.EventLog` — JSONL event sink (``events.jsonl``)
  with run-id / host / pid / role stamped on every record, plus a
  console sink that preserves the drivers' human-readable lines;
- :class:`~.metrics.MetricsRegistry` — counters, gauges, fixed-bucket
  histograms with labels, exported as Prometheus text exposition
  (``metrics.prom``) and a JSON snapshot (``metrics.json``);
- :class:`~.trace.Tracer` — nestable ``perf_counter`` spans exported
  as Chrome trace-event JSON (``trace.json``), loadable in Perfetto.

Job-level plane (ISSUE 5): per-host directories merge into one
``obs/job/`` view (:mod:`~.collect` — fetched over the exec/copy
fabric, so chaos + retry cover collection), analytics compute
skew/straggler/stall/lost findings and a live health snapshot
(:mod:`~.analyze`), and ``tpu-doctor`` (:mod:`~.doctor`) renders the
diagnosis. Those modules are imported directly, not re-exported here
— the fabric import would cycle through this package.

Live plane (ISSUE 11): :mod:`~.tracectx` carries a trace context
across process (``TPU_OPERATOR_TRACE_*`` env) and thread boundaries
so one request/step reads as one span tree in the merged trace;
:mod:`~.live` streams rolling-window aggregates over a ``/livez``
HTTP sidecar; :mod:`~.slo` evaluates burn-rate SLOs whose breaches
drive serve-side load shedding; :mod:`~.top` (``tpu-top``) renders
the per-host live table. Also imported directly, not re-exported.

Process model: the workflow driver calls :func:`obs_run` (or
:func:`init_obs`) to root the run's artifacts — by default under
``<workspace>/obs`` — and exports ``TPU_OPERATOR_OBS_DIR`` /
``TPU_OPERATOR_OBS_RUN`` so every child process the fabric spawns
attaches to the SAME run via :func:`get_obs`. Flushes are per-process
idempotent merges (see metrics/trace modules), so a chaos-killed
trainer's last flush and its resumed successor's both land.

Stdlib-only: the control-plane image imports this (kubeshim is
stdlib-only by contract) — no numpy, no jax, no third-party deps.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import socket
import threading
import time
import uuid
from typing import Optional

from dgl_operator_tpu.obs.events import EVENTS_JSONL, EventLog  # noqa: F401
from dgl_operator_tpu.obs.metrics import (DEFAULT_BUCKETS, LATENCY_BUCKETS,  # noqa: F401
                                          METRICS_JSON,
                                          METRICS_PROM, Counter, Gauge,
                                          Histogram, MetricsRegistry,
                                          merge_snapshots,
                                          quantile_from_counts,
                                          render_prometheus)
from dgl_operator_tpu.obs import metrics as _metrics_mod
from dgl_operator_tpu.obs.trace import TRACE_JSON, Tracer, write_chrome  # noqa: F401

OBS_DIR_ENV = "TPU_OPERATOR_OBS_DIR"
OBS_RUN_ENV = "TPU_OPERATOR_OBS_RUN"
OBS_ROLE_ENV = "TPU_OPERATOR_OBS_ROLE"


def _gen_run_id() -> str:
    return (time.strftime("%Y%m%dT%H%M%S") + "-"
            + uuid.uuid4().hex[:6])


class Obs:
    """One process's telemetry bundle: event log + metrics registry +
    tracer, rooted (optionally) at a per-run directory. With no
    directory everything still works in memory — console lines print,
    metrics accumulate for tests — and :meth:`flush` is a no-op."""

    def __init__(self, directory: Optional[str] = None,
                 run_id: Optional[str] = None, role: str = "main",
                 console: bool = True):
        self.directory = os.path.abspath(directory) if directory else None
        if self.directory:
            try:
                os.makedirs(self.directory, exist_ok=True)
            except OSError as exc:
                # an unwritable workspace must not fail the job — it
                # only costs the run its telemetry files
                print(f"obs: cannot create {self.directory} ({exc}); "
                      "telemetry stays in-memory", flush=True)
                self.directory = None
        self.run_id = run_id or _gen_run_id()
        self.role = role
        self.host = socket.gethostname()
        self.pid = os.getpid()
        self.events = EventLog(
            path=(os.path.join(self.directory, EVENTS_JSONL)
                  if self.directory else None),
            console=console,
            base={"run": self.run_id, "host": self.host,
                  "pid": self.pid, "role": role})
        self.metrics = MetricsRegistry()
        self.tracer = Tracer(
            process_name=f"{role} ({self.host}:{self.pid})")

    @property
    def proc_id(self) -> str:
        return f"{self.host}:{self.pid}:{self.role}"

    def flush(self) -> None:
        """Publish metrics + trace artifacts (merge-idempotent; events
        append live). Never raises — telemetry must not fail the job."""
        if not self.directory:
            return
        if not os.path.isdir(self.directory):
            # the run directory was cleaned up (test teardown, a
            # reaped workspace) — nothing left to flush into
            return
        try:
            _metrics_mod.write_files(self.directory, self.proc_id,
                                     self.metrics.snapshot(),
                                     run_id=self.run_id)
            write_chrome(self.directory, self.tracer)
        except OSError as exc:
            print(f"obs: flush to {self.directory} failed ({exc})",
                  flush=True)


_lock = threading.Lock()
_obs: Optional[Obs] = None
_atexit_registered = False


def _flush_global() -> None:
    if _obs is not None:
        _obs.flush()


def _register_atexit() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_flush_global)


def init_obs(directory: Optional[str] = None,
             run_id: Optional[str] = None, role: str = "main",
             console: bool = True, export_env: bool = True) -> Obs:
    """Install the process-global :class:`Obs` (flushing any previous
    one). ``export_env`` publishes the directory and run id into the
    environment so child processes spawned by the fabric attach to the
    same run through :func:`get_obs`."""
    global _obs
    with _lock:
        if _obs is not None:
            _obs.flush()
        _obs = Obs(directory, run_id=run_id, role=role, console=console)
        if export_env and _obs.directory:
            os.environ[OBS_DIR_ENV] = _obs.directory
            os.environ[OBS_RUN_ENV] = _obs.run_id
        _register_atexit()
        return _obs


def get_obs() -> Obs:
    """The process-global :class:`Obs`, created lazily from the
    environment (``TPU_OPERATOR_OBS_DIR`` / ``_RUN`` / ``_ROLE``) and
    re-synced whenever the env directory changes — an emitter never
    holds a stale run's sinks after the driver moved on."""
    global _obs
    env_dir = os.environ.get(OBS_DIR_ENV) or None
    want = os.path.abspath(env_dir) if env_dir else None
    cur = _obs
    if cur is not None and cur.directory == want:
        return cur
    with _lock:
        if _obs is not None and _obs.directory == want:
            return _obs
        if _obs is not None:
            _obs.flush()
        _obs = Obs(want, run_id=os.environ.get(OBS_RUN_ENV),
                   role=os.environ.get(OBS_ROLE_ENV, "proc"))
        _register_atexit()
        return _obs


@contextlib.contextmanager
def obs_run(directory: str, role: str, run_id: Optional[str] = None,
            console: bool = True):
    """Driver-scoped telemetry run: init + env export on entry (child
    processes inherit the run), flush + env restore on exit — an
    in-process caller (tests, notebooks) leaves no env pollution."""
    prev = {k: os.environ.get(k) for k in (OBS_DIR_ENV, OBS_RUN_ENV)}
    obs = init_obs(directory, run_id=run_id or os.environ.get(OBS_RUN_ENV),
                   role=role, console=console)
    try:
        yield obs
    finally:
        obs.flush()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
