"""Edge-score predictors for link prediction.

Parity with the reference's link-prediction heads
(examples/GraphSAGE/code/4_link_predict.py:130-145 DotPredictor,
:204-240 MLPPredictor) expressed through gsddmm instead of
apply_edges UDFs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from dgl_operator_tpu.graph.graph import DeviceGraph
from dgl_operator_tpu import ops


class DotPredictor(nn.Module):
    """score(u,v) = h_u . h_v"""

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        return ops.u_dot_v(g, h, h)[:, 0]


class MLPPredictor(nn.Module):
    """score(u,v) = MLP([h_u || h_v])"""

    hidden: int

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        h = jnp.asarray(h)
        cat = jnp.concatenate([h[g.src], h[g.dst]], axis=-1)
        x = nn.relu(nn.Dense(self.hidden)(cat))
        return nn.Dense(1)(x)[:, 0]
