from dgl_operator_tpu.nn.conv import (  # noqa: F401
    GraphConv, SAGEConv, GATConv, GATv2Conv, GINConv, RelGraphConv,
    FanoutSAGEConv, FanoutGATConv, FanoutGATv2Conv, WeightedSAGEConv)
from dgl_operator_tpu.nn.predictors import DotPredictor, MLPPredictor  # noqa: F401
from dgl_operator_tpu.nn.kge import (  # noqa: F401
    transe_score, distmult_score, complex_score, rotate_score, KGE_SCORERS)
