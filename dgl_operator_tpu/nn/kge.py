"""Knowledge-graph-embedding scorers (DGL-KE model family).

Parity with the models the reference trains through dglke_dist_train
(python/dglrun/exec/dglkerun:284-304 runs ComplEx; the hotfixed DGL-KE
server accepts TransE/TransE_l1/TransE_l2/TransR/RESCAL/DistMult/
ComplEx/RotatE — kvserver.py:66-67 — all of which exist here, plus
SimplE from the dgl-ke master the reference's image builds
(examples/DGL-KE/Dockerfile:55); TransR and RESCAL pack their
per-relation matrices into wider relation rows, see
``relation_dim``). Scorers are pure functions of
(head, rel, tail) embedding blocks so they jit/vmap cleanly and run in
both the positive path and the chunked-negative path.

Shapes: positive scoring takes [B, D]; negative scoring takes heads (or
tails) of shape [C, N, D] against [C, chunk, D] positives, producing
[C, chunk, N] — the chunked negative-sampling layout of the reference's
sampler (examples/DGL-KE/hotfix/sampler.py:346-419: batch split into
chunks, ``neg_sample_size`` shared per chunk). The [C, chunk, N] matmul
form is exactly an MXU batched GEMM.
"""

from __future__ import annotations

import jax.numpy as jnp


def _split2(x):
    d = x.shape[-1] // 2
    return x[..., :d], x[..., d:]


def transe_score(h, r, t, gamma: float = 12.0, p: int = 1):
    """gamma - ||h + r - t||_p"""
    d = h + r - t
    if p == 1:
        dist = jnp.abs(d).sum(-1)
    else:
        dist = jnp.sqrt((d * d).sum(-1) + 1e-10)
    return gamma - dist


def distmult_score(h, r, t, gamma: float = 0.0):
    return (h * r * t).sum(-1)


def complex_score(h, r, t, gamma: float = 0.0):
    hr, hi = _split2(h)
    rr, ri = _split2(r)
    tr, ti = _split2(t)
    return ((hr * rr - hi * ri) * tr + (hr * ri + hi * rr) * ti).sum(-1)


def rotate_score(h, r, t, gamma: float = 12.0, emb_init: float = 1.0):
    """gamma - ||h o e^{i r} - t||_2 with r as phase angles.

    Canonical relation dim is D/2 (one phase per complex component);
    full-width relation tables are accepted by reading the first D/2
    columns, so entity/relation tables can share a dim."""
    hr, hi = _split2(h)
    tr, ti = _split2(t)
    half = h.shape[-1] // 2
    phase = r[..., :half] / (emb_init / jnp.pi)
    rr, ri = jnp.cos(phase), jnp.sin(phase)
    dr = hr * rr - hi * ri - tr
    di = hr * ri + hi * rr - ti
    dist = jnp.sqrt(dr * dr + di * di + 1e-10).sum(-1)
    return gamma - dist


def rescal_score(h, r, t, gamma: float = 0.0):
    """Bilinear h^T M_r t with the relation as a full [D, D] matrix
    (relation rows are the flattened matrix, width D*D — the reference
    serves it from the same KVStore tables, kvserver.py model choices).
    Similarity semantics like DistMult: no gamma term."""
    d = h.shape[-1]
    M = r.reshape(r.shape[:-1] + (d, d))
    return (h * jnp.einsum("...ij,...j->...i", M, t)).sum(-1)


def transr_score(h, r, t, gamma: float = 12.0):
    """TransE in a per-relation projected space: relation rows pack the
    [D, D] projection (flattened) followed by the D-dim translation
    (width D*D + D). score = gamma - ||h M_r + r_t - t M_r||_1
    (L1, DGL-KE's TransRScore distance)."""
    d = h.shape[-1]
    M = r[..., : d * d].reshape(r.shape[:-1] + (d, d))
    rt = r[..., d * d:]
    hp = jnp.einsum("...i,...ij->...j", h, M)
    tp = jnp.einsum("...i,...ij->...j", t, M)
    return gamma - jnp.abs(hp + rt - tp).sum(-1)


def simple_score(h, r, t, gamma: float = 0.0):
    """SimplE (Kazemi & Poole 2018): entity rows pack (head-role,
    tail-role) halves, relation rows pack (forward, inverse) halves;
    score = 1/2 [<h_head, r, t_tail> + <t_head, r_inv, h_tail>].
    Similarity semantics like DistMult — no gamma term. Parity:
    awslabs/dgl-ke SimplEScore.edge_func (the reference's DGL-KE image
    builds dgl-ke master, examples/DGL-KE/Dockerfile:55)."""
    hi, hj = _split2(h)
    ti, tj = _split2(t)
    rf, rv = _split2(r)
    return 0.5 * (hi * rf * tj + ti * rv * hj).sum(-1)


KGE_SCORERS = {
    "TransE": transe_score,
    "TransE_l1": lambda h, r, t, **kw: transe_score(h, r, t, p=1, **kw),
    "TransE_l2": lambda h, r, t, **kw: transe_score(h, r, t, p=2, **kw),
    "DistMult": distmult_score,
    "ComplEx": complex_score,
    "RotatE": rotate_score,
    "RESCAL": rescal_score,
    "TransR": transr_score,
    "SimplE": simple_score,
}


def relation_dim(model_name: str, hidden_dim: int) -> int:
    """Relation-table row width per scorer (entity tables are always
    ``hidden_dim``): RESCAL rows hold a flattened [D, D] matrix, TransR
    additionally packs the D-dim translation."""
    if model_name == "RESCAL":
        return hidden_dim * hidden_dim
    if model_name == "TransR":
        return hidden_dim * hidden_dim + hidden_dim
    return hidden_dim


def neg_score(scorer, pos_part, r, neg, chunk: int, neg_mode: str = "tail",
              **kw):
    """Chunked negative scoring.

    pos_part: [B, D] the fixed side (heads for tail-negatives and vice
    versa); r: [B, D_r]; neg: [C, N, D] candidate replacements where
    C = B // chunk. Returns [B, N].

    RotatE's phase for the relation of each positive is applied to the
    fixed side; for DistMult/ComplEx the contraction reduces to a
    batched GEMM against the negative block (MXU path).
    """
    B = pos_part.shape[0]
    C = neg.shape[0]
    n = neg.shape[1]
    pp = pos_part.reshape(C, chunk, -1)
    rr = r.reshape(C, chunk, -1)
    if scorer in (distmult_score, complex_score, simple_score):
        # reduce to left . neg — one batched GEMM on the MXU. The "left"
        # vector depends on which side is negated (ComplEx and SimplE
        # are not symmetric in h/t).
        if scorer is distmult_score:
            left = pp * rr                       # [C, chunk, D]
        elif scorer is simple_score:
            r_f, r_v = _split2(rr)
            p_i, p_j = _split2(pp)
            if neg_mode == "tail":  # pp is h; neg rows are [t_i || t_j]
                left = 0.5 * jnp.concatenate([r_v * p_j, r_f * p_i], -1)
            else:                   # pp is t; neg rows are [h_i || h_j]
                left = 0.5 * jnp.concatenate([r_f * p_j, r_v * p_i], -1)
        else:
            pr, pi = _split2(pp)
            r_r, r_i = _split2(rr)
            if neg_mode == "tail":  # pp is h: score = f(h, r) . [tr||ti]
                left = jnp.concatenate([pr * r_r - pi * r_i,
                                        pr * r_i + pi * r_r], -1)
            else:                   # pp is t: score = g(t, r) . [hr||hi]
                left = jnp.concatenate([r_r * pr + r_i * pi,
                                        r_r * pi - r_i * pr], -1)
        out = jnp.einsum("ckd,cnd->ckn", left, neg)  # batched GEMM
    elif neg_mode == "tail":
        out = scorer(pp[:, :, None, :], rr[:, :, None, :],
                     neg[:, None, :, :], **kw)       # [C, chunk, N]
    else:
        out = scorer(neg[:, None, :, :], rr[:, :, None, :],
                     pp[:, :, None, :], **kw)
    return out.reshape(B, n)
