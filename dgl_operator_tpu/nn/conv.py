"""Graph convolution layers (flax.linen).

Capability parity with the conv layers the reference's workloads use —
GraphConv/GCN (examples/GraphSAGE/code/1_introduction.py:114-121),
SAGEConv incl. a hand-written weighted variant
(3_message_passing.py:85-141,233-268), GATConv-style attention (listed
in BASELINE.json configs), GINConv (5_graph_classification.py:150-170),
and RelGraphConv for heterograph link prediction — re-built on the
TPU primitives in ``dgl_operator_tpu.ops``:

- full-graph layers consume a ``DeviceGraph`` (dst-sorted padded edge
  list) and use segment reductions;
- sampled-path layers (``FanoutSAGEConv``) consume a ``FanoutBlock``
  and use dense masked reductions that fuse into the MXU matmuls.

Dtype policy: parameters float32, activations configurable (bfloat16
recommended on TPU); reductions accumulate in float32.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgl_operator_tpu.graph.graph import DeviceGraph
from dgl_operator_tpu.graph.blocks import FanoutBlock, Block
from dgl_operator_tpu import ops


class GraphConv(nn.Module):
    """Kipf-Welling GCN layer: ``H' = D^-1/2 A D^-1/2 H W`` (norm='both')."""

    out_feats: int
    norm: str = "both"  # 'both' | 'right' | 'none'
    use_bias: bool = True

    @nn.compact
    def __call__(self, g: DeviceGraph, h, in_deg=None, out_deg=None):
        # degrees: computed on the fly if not supplied (counts valid edges)
        nseg = g.num_nodes + 1
        ones = jnp.asarray(g.edge_mask)
        if in_deg is None:
            in_deg = ops.segment_sum(ones, jnp.asarray(g.dst), nseg,
                                     sorted=g.sorted_by_dst)[: g.num_nodes]
        if out_deg is None:
            out_deg = ops.segment_sum(ones, jnp.asarray(g.src), nseg,
                                      sorted=False)[: g.num_nodes]
        if self.norm == "both":
            h = h * (jnp.maximum(out_deg, 1.0) ** -0.5)[:, None]
        # project first when it shrinks the message width (standard GCN
        # trick; XLA cannot reorder across the gather)
        w = nn.Dense(self.out_feats, use_bias=False, name="weight")
        if h.shape[-1] > self.out_feats:
            h = w(h)
            agg = ops.gspmm(g, "copy_u", "sum", ufeat=h)
        else:
            agg = w(ops.gspmm(g, "copy_u", "sum", ufeat=h))
        if self.norm in ("both", "right"):
            scale = (jnp.maximum(in_deg, 1.0)
                     ** (-0.5 if self.norm == "both" else -1.0))
            agg = agg * scale[:, None]
        if self.use_bias:
            agg = agg + self.param("bias", nn.initializers.zeros,
                                   (self.out_feats,))
        return agg


class SAGEConv(nn.Module):
    """GraphSAGE layer, full-graph form (aggregator: mean/pool/sum).

    ``H' = W_self h  +  W_neigh agg_{u->v} h_u`` — the reference's
    hand-rolled SAGEConv does exactly this with mean
    (3_message_passing.py:85-141)."""

    out_feats: int
    aggregator: str = "mean"

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        if self.aggregator == "pool":
            h_msg = nn.relu(nn.Dense(h.shape[-1], name="pool")(h))
            agg = ops.gspmm(g, "copy_u", "max", ufeat=h_msg)
        else:
            agg = ops.gspmm(g, "copy_u", self.aggregator, ufeat=h)
        return (nn.Dense(self.out_feats, name="self")(h)
                + nn.Dense(self.out_feats, use_bias=False, name="neigh")(agg))


class WeightedSAGEConv(nn.Module):
    """SAGE with per-edge scalar weights (reference UDF variant:
    3_message_passing.py:233-268 ``u_mul_e`` then mean)."""

    out_feats: int

    @nn.compact
    def __call__(self, g: DeviceGraph, h, ew):
        agg = ops.gspmm(g, "u_mul_e", "mean", ufeat=h, efeat=ew)
        return (nn.Dense(self.out_feats, name="self")(h)
                + nn.Dense(self.out_feats, use_bias=False, name="neigh")(agg))


class FanoutSAGEConv(nn.Module):
    """GraphSAGE layer on a sampled ``FanoutBlock`` (the TPU hot path).

    Aggregation is a masked mean over the dense [num_dst, fanout, D]
    gather — zero scatter ops; everything fuses into the two matmuls.
    The dst representation uses the seed-prefix invariant
    (h_dst = h_src[:num_dst], reference train_dist.py:87-94).

    ``dtype`` sets the computation dtype (mixed precision): with
    ``jnp.bfloat16`` the gather/reduce and both GEMMs run at the v5e
    MXU's native width while parameters stay float32 (flax
    ``param_dtype`` default) — the standard bf16-compute / f32-master
    recipe. None keeps full float32."""

    out_feats: int
    aggregator: str = "mean"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, block: FanoutBlock, h_src):
        if self.dtype is not None:
            h_src = h_src.astype(self.dtype)
        h_dst = h_src[: block.num_dst]
        if self.aggregator == "mean":
            agg = ops.fanout_mean(block, h_src)
        elif self.aggregator == "sum":
            agg = ops.fanout_sum(block, h_src)
        elif self.aggregator == "pool":
            hp = nn.relu(nn.Dense(h_src.shape[-1], name="pool",
                                  dtype=self.dtype)(h_src))
            agg = ops.fanout_max(block, hp)
        else:
            raise ValueError(self.aggregator)
        if self.dtype is not None:
            agg = agg.astype(self.dtype)
        return (nn.Dense(self.out_feats, name="self",
                         dtype=self.dtype)(h_dst)
                + nn.Dense(self.out_feats, use_bias=False, name="neigh",
                           dtype=self.dtype)(agg))


def gat_projection_raw(layer_params, h):
    """Raw-param twin of :func:`_gat_projection` for inference paths
    that drive a trained fc/attn_l/attn_r subtree outside a flax module
    (distributed layer-wise eval, hub-node ring attention). Returns
    ``(feat [N, H, D], el [N, H], er [N, H])``."""
    al = jnp.asarray(layer_params["attn_l"])
    ar = jnp.asarray(layer_params["attn_r"])
    H, D = al.shape[-2], al.shape[-1]
    feat = (jnp.asarray(h) @ jnp.asarray(
        layer_params["fc"]["kernel"])).reshape((-1, H, D))
    return feat, (feat * al).sum(-1), (feat * ar).sum(-1)


def gatv2_projection_raw(layer_params, h):
    """Raw-param GATv2 projections for inference paths driving a
    trained fc_src/fc_dst/attn subtree outside a flax module
    (distributed layer-wise eval). Returns ``(fs [N, H, D],
    fd [N, H, D], attn [1, H, D])``."""
    attn = jnp.asarray(layer_params["attn"])
    H, D = attn.shape[-2], attn.shape[-1]
    h = jnp.asarray(h)
    fs = (h @ jnp.asarray(
        layer_params["fc_src"]["kernel"])).reshape((-1, H, D))
    fd = (h @ jnp.asarray(
        layer_params["fc_dst"]["kernel"])).reshape((-1, H, D))
    return fs, fd, attn


def _gat_projection(mod: nn.Module, h, H: int, D: int, dtype=None):
    """fc/attn_l/attn_r projection of GATConv (additive attention split
    into src/dst halves: a^T [Wh_u || Wh_v]). ``dtype`` runs the
    projection matmul + attention reductions in that width (bf16 mixed
    precision) with f32 master params (flax param_dtype default).

    NOTE: FanoutGATConv declares the SAME parameter structure inline
    (its reassociated compute order can't route through this helper);
    the two declarations must stay byte-identical — the
    sampled-vs-full-graph parity test (tests/test_nn.py::
    test_fanout_gat_matches_full_graph_gat) pins that, so a change to
    either site must update both or that test fails."""
    if dtype is not None:
        h = h.astype(dtype)
    feat = nn.Dense(H * D, use_bias=False, name="fc",
                    dtype=dtype)(h).reshape((-1, H, D))
    al = mod.param("attn_l", nn.initializers.glorot_uniform(),
                   (1, H, D))
    ar = mod.param("attn_r", nn.initializers.glorot_uniform(),
                   (1, H, D))
    if dtype is not None:
        al, ar = al.astype(dtype), ar.astype(dtype)
    # reductions accumulate in f32 regardless of compute dtype (the
    # module's mixed-precision contract; logits are consumed in f32)
    return (feat, (feat * al).sum(-1, dtype=jnp.float32),
            (feat * ar).sum(-1, dtype=jnp.float32))


def _masked_fanout_softmax(logits, mask, dtype):
    """Shared GAT/GATv2 fanout-softmax: padded slots to -inf, softmax
    over the fanout axis, all-masked rows (isolated dsts) zeroed, α
    cast to the compute dtype. ``logits`` [nd, F, H] in f32."""
    logits = jnp.where(mask[..., None] > 0, logits, -jnp.inf)
    alpha = jax.nn.softmax(logits, axis=1)
    alpha = jnp.where(jnp.isfinite(alpha), alpha, 0.0)
    return alpha if dtype is None else alpha.astype(dtype)


def _edge_softmax_aggregate(g: DeviceGraph, logits, feat_src, H, D,
                            concat_heads):
    """Shared GAT/GATv2 tail: masked per-destination edge-softmax over
    ``logits`` [E, H], α-weighted sum of ``feat_src`` messages.
    Padded edges point at the spare segment AND are masked to -inf so
    they can't contribute; isolated destinations read 0."""
    alpha = ops.segment_softmax(
        jnp.where(jnp.asarray(g.edge_mask)[:, None] > 0, logits, -jnp.inf),
        jnp.asarray(g.dst), g.num_nodes + 1, sorted=g.sorted_by_dst)
    alpha = jnp.where(jnp.isfinite(alpha), alpha, 0.0)
    msg = feat_src[g.src] * alpha[..., None]
    out = ops.segment_sum(msg, jnp.asarray(g.dst), g.num_nodes + 1,
                          sorted=g.sorted_by_dst)[: g.num_nodes]
    return out.reshape((-1, H * D)) if concat_heads else out.mean(1)


class GATConv(nn.Module):
    """Graph attention layer (multi-head, LeakyReLU attention logits,
    per-destination softmax via ``segment_softmax``)."""

    out_feats: int
    num_heads: int = 1
    negative_slope: float = 0.2
    concat_heads: bool = True

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        H, D = self.num_heads, self.out_feats
        feat, el, er = _gat_projection(self, h, H, D)
        logits = nn.leaky_relu(el[g.src] + er[g.dst],
                               negative_slope=self.negative_slope)
        return _edge_softmax_aggregate(g, logits, feat, H, D,
                                       self.concat_heads)


class GATv2Conv(nn.Module):
    """GATv2 ("How Attentive Are Graph Attention Networks?", Brody et
    al.): the attention vector applies AFTER the LeakyReLU of the
    combined projections, restoring dynamic attention — DGL's
    GATv2Conv semantics with separate src/dst projections
    (share_weights=False). Same DeviceGraph edge-softmax machinery as
    :class:`GATConv`."""

    out_feats: int
    num_heads: int = 1
    negative_slope: float = 0.2
    concat_heads: bool = True

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        H, D = self.num_heads, self.out_feats
        fs = nn.Dense(H * D, use_bias=False, name="fc_src")(h)
        fs = fs.reshape((-1, H, D))
        fd = nn.Dense(H * D, use_bias=False, name="fc_dst")(h)
        fd = fd.reshape((-1, H, D))
        attn = self.param("attn", nn.initializers.glorot_uniform(),
                          (1, H, D))
        e = nn.leaky_relu(fs[g.src] + fd[g.dst],
                          negative_slope=self.negative_slope)
        logits = (e * attn).sum(-1)                    # [E, H]
        return _edge_softmax_aggregate(g, logits, fs, H, D,
                                       self.concat_heads)


class FanoutGATConv(nn.Module):
    """GAT attention on a sampled ``FanoutBlock`` — the TPU-native
    sampled-path form of :class:`GATConv` (BASELINE.md "SDDMM attention
    on TPU"). The dense ``[num_dst, fanout]`` neighbor table turns the
    edge-softmax into a plain masked softmax over the fanout axis: no
    segment ops at all, everything batches onto the MXU/VPU. Parameter
    structure (fc / attn_l / attn_r) is IDENTICAL to GATConv, so
    sampled-trained parameters drop into full-graph inference and the
    two are numerics-parity-testable (tests/test_nn.py)."""

    out_feats: int
    num_heads: int = 1
    negative_slope: float = 0.2
    concat_heads: bool = True
    # bf16 mixed precision (f32 master params; softmax runs in f32
    # for numerical headroom, the matmuls/gathers in `dtype`)
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, block: FanoutBlock, h_src):
        H, D = self.num_heads, self.out_feats
        nd = block.num_dst
        x = h_src if self.dtype is None else h_src.astype(self.dtype)
        # GAT attention is LINEAR in (W x_u), which licenses two exact
        # reassociations that remove the layer's F-times-larger terms
        # (the naive form projected every sampled source row and
        # gathered [nd, F, H, D] projected features — the r3 CPU
        # edge-softmax collapse, VERDICT r3 weak #8):
        #   a_l · (W_h x_u) = x_u · (W_hᵀ a_l)   -> per-source logits
        #     from one thin [Din, H] matmul, no source-side projection;
        #   Σ_f α (W_h x_u) = W_h (Σ_f α x_u)    -> aggregate RAW
        #     neighbor features, project once per dst row.
        # Parameter structure (fc / attn_l / attn_r) duplicates
        # _gat_projection's declarations byte-for-byte (same names,
        # shapes, initializers) — the GATConv parity test pins the
        # drop-in compatibility; edit both sites together.
        dense = nn.Dense(H * D, use_bias=False, name="fc",
                         dtype=self.dtype)
        feat_dst = dense(x[:nd]).reshape((-1, H, D))   # needed for a_r
        al = self.param("attn_l", nn.initializers.glorot_uniform(),
                        (1, H, D))
        ar = self.param("attn_r", nn.initializers.glorot_uniform(),
                        (1, H, D))
        kernel = dense.variables["params"]["kernel"]
        # cl folds a D-wide reduction: compute it from the f32 MASTER
        # params before any bf16 cast (the module's f32-accumulation
        # contract — a bf16 sum here would poison every logit)
        cl = (kernel.reshape((-1, H, D))
              * al[0]).sum(-1, dtype=jnp.float32)      # [Din, H]
        if self.dtype is not None:
            ar = ar.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
        k3 = kernel.reshape((-1, H, D))                # [Din, H, D]
        el = jnp.einsum("ni,ih->nh", x, cl,
                        preferred_element_type=jnp.float32)
        er = (feat_dst * ar).sum(-1, dtype=jnp.float32)    # [nd, H]
        nbr = jnp.asarray(block.nbr)                   # [nd, F]
        mask = jnp.asarray(block.mask)                 # [nd, F]
        logits = nn.leaky_relu(el[nbr] + er[:, None, :],
                               negative_slope=self.negative_slope)
        alpha = _masked_fanout_softmax(logits, mask, self.dtype)
        # per-head static loop of plain ops instead of h-batched
        # einsums ('nfh,nfi->nhi' / 'nhi,iho->nho' lower to tiny
        # batched matmuls that run ~7x slower on CPU; the unrolled
        # form is the same MXU GEMMs on TPU). The gather x[nbr] is
        # shared — XLA fuses the weighted sums over it into one pass.
        g = x[nbr]                                     # [nd, F, Din]
        heads = []
        for h in range(H):
            z_h = (alpha[:, :, h, None] * g).sum(
                axis=1, dtype=jnp.float32)             # [nd, Din]
            if self.dtype is not None:
                z_h = z_h.astype(self.dtype)
            heads.append(jnp.einsum("ni,io->no", z_h, k3[:, h, :],
                                    preferred_element_type=jnp.float32))
        out = jnp.stack(heads, axis=1)                 # [nd, H, D]
        if self.dtype is not None:
            out = out.astype(self.dtype)
        return (out.reshape((-1, H * D)) if self.concat_heads
                else out.mean(1))


class FanoutGATv2Conv(nn.Module):
    """GATv2 on a sampled ``FanoutBlock`` — the sampled-path form of
    :class:`GATv2Conv` with the SAME parameter structure
    (fc_src / fc_dst / attn), so sampled-trained parameters drop into
    full-graph inference (parity-tested like the GAT pair).

    Unlike :class:`FanoutGATConv` there is no thin-matmul
    reassociation: v2 applies the attention vector AFTER the LeakyReLU
    precisely so the score is NOT linear in the projections — the
    gathered ``[nd, F, H, D]`` combine is inherent to the model, the
    compute price of dynamic attention."""

    out_feats: int
    num_heads: int = 1
    negative_slope: float = 0.2
    concat_heads: bool = True
    # bf16 mixed precision: f32 master params, softmax logits and
    # accumulations in f32 (module dtype convention)
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, block: FanoutBlock, h_src):
        H, D = self.num_heads, self.out_feats
        nd = block.num_dst
        x = h_src if self.dtype is None else h_src.astype(self.dtype)
        fs = nn.Dense(H * D, use_bias=False, name="fc_src",
                      dtype=self.dtype)(x).reshape((-1, H, D))
        fd = nn.Dense(H * D, use_bias=False, name="fc_dst",
                      dtype=self.dtype)(x[:nd]).reshape((-1, H, D))
        attn = self.param("attn", nn.initializers.glorot_uniform(),
                          (1, H, D))
        if self.dtype is not None:
            attn = attn.astype(self.dtype)
        nbr = jnp.asarray(block.nbr)                    # [nd, F]
        mask = jnp.asarray(block.mask)                  # [nd, F]
        e = nn.leaky_relu(fs[nbr] + fd[:, None],        # [nd, F, H, D]
                          negative_slope=self.negative_slope)
        logits = (e * attn).sum(-1, dtype=jnp.float32)  # [nd, F, H]
        alpha = _masked_fanout_softmax(logits, mask, self.dtype)
        out = (alpha[..., None] * fs[nbr]).sum(axis=1,
                                               dtype=jnp.float32)
        if self.dtype is not None:
            out = out.astype(self.dtype)
        return (out.reshape((-1, H * D)) if self.concat_heads
                else out.mean(1))


class GINConv(nn.Module):
    """Graph isomorphism layer: ``h' = MLP((1+eps) h + sum_nbr h)``."""

    mlp: Callable
    learn_eps: bool = True

    @nn.compact
    def __call__(self, g: DeviceGraph, h):
        agg = ops.gspmm(g, "copy_u", "sum", ufeat=h)
        eps = (self.param("eps", nn.initializers.zeros, ())
               if self.learn_eps else 0.0)
        return self.mlp((1.0 + eps) * h + agg)


class RelGraphConv(nn.Module):
    """Relational GCN with basis decomposition (heterograph message
    passing for the link-predict workload family).

    Edge types select a per-relation weight composed from ``num_bases``
    shared bases; messages are W_r h_u, mean-aggregated per destination.
    The einsum keeps all relations' projections as one batched matmul
    (MXU-friendly) instead of a Python loop over relations.
    """

    out_feats: int
    num_rels: int
    num_bases: int = 0
    self_loop: bool = True

    @nn.compact
    def __call__(self, g: DeviceGraph, h, etype):
        B = self.num_bases if self.num_bases > 0 else self.num_rels
        basis = self.param("basis", nn.initializers.glorot_uniform(),
                           (B, h.shape[-1], self.out_feats))
        if self.num_bases > 0:
            coef = self.param("coef", nn.initializers.glorot_uniform(),
                              (self.num_rels, B))
            w = jnp.einsum("rb,bio->rio", coef, basis)
        else:
            w = basis
        msg = jnp.einsum("ei,eio->eo", h[g.src], w[etype])
        agg = ops.segment_mean(
            msg * jnp.asarray(g.edge_mask)[:, None], jnp.asarray(g.dst),
            g.num_nodes + 1, sorted=g.sorted_by_dst)[: g.num_nodes]
        if self.self_loop:
            agg = agg + nn.Dense(self.out_feats, use_bias=False,
                                 name="loop")(h)
        return agg
