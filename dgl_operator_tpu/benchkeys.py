"""The pinned benchmark-record key catalogues — ONE source of truth.

Until ISSUE 10 these tuples lived as three (and a half) copies:
``bench.py`` pinned ``_SCALE_FULL_KEYS``/``_SERVE_KEYS``/``_TUNE_KEYS``
for its ``detail.*`` summaries, ``benchmarks/bench_scaling.py`` and
``benchmarks/bench_tune.py`` re-spelled their own, and
``tests/test_bench_harness.py`` asserted the copies stayed equal —
i.e. drift was caught only when the right test ran. Now every consumer
(bench.py, the benchmarks, the pinned-key tests) aliases THESE tuples,
and tpu-lint rule TPU006 flags any module that re-defines one as a
literal, so a drifted copy cannot even parse past CI.

Stdlib-only and import-light on purpose: bench.py and the benchmark
scripts import it before jax is configured.

Renaming a key here is an interface change: the tracked JSON artifacts
(benchmarks/SCALE_FULL.json, SERVE.json, TUNE.json, RING_SCALING.json)
and every harness reading them consume these names.
"""

# scale-record keys every bench line must carry forward — the memory-
# scaling evidence (owner-layout footprint + exchange cost + ZeRO
# state bytes) of the round's only hardware record
SCALE_FULL_KEYS = ("halo_exchange_mib_per_step", "feats_slot_owner_mib",
                   "feats_slot_replicated_mib",
                   "exchange_staging_mib_per_slot",
                   # rule-driven state sharding (ISSUE 8): replicated
                   # vs ZeRO/rules per-slot params + optimizer bytes
                   "params_mib_per_slot_replicated",
                   "params_mib_per_slot_sharded",
                   "opt_state_mib_per_slot_replicated",
                   "opt_state_mib_per_slot_sharded",
                   # ZeRO-3 persistent param residency (ISSUE 16):
                   # the flat-shard per-slot bill and its ratio to the
                   # replicated baseline (acceptance: <= 0.30 at 8
                   # parts; shardrules.zero3_bytes_per_slot owns the
                   # byte model)
                   "params_mib_per_slot_zero3",
                   "params_zero3_vs_replicated",
                   # quantized feature plane + out-of-core partitioner
                   # (ISSUE 17): owner-store slot bill per storage
                   # dtype, the int8-vs-fp32 ratio (acceptance:
                   # <= 0.30 — codes plus the [D] scale/zero sidecar
                   # tiles), the quantized halo-exchange bill, and the
                   # partitioner peak-RSS ratio of the ooc arm to the
                   # in-memory arm (acceptance: <= 0.5 at equal cut;
                   # benchmarks/bench_scale_full.py --ooc-arm)
                   "feats_mib_per_slot_float32",
                   "feats_mib_per_slot_bfloat16",
                   "feats_mib_per_slot_int8",
                   "feats_int8_vs_float32",
                   "halo_exchange_mib_per_step_int8",
                   "ooc_peak_rss_vs_inmem")

# headline keys of the ring-scaling record (benchmarks/bench_scaling.py)
SCALING_KEYS = ("eps_1", "eps_8", "eps_8_owner_layout",
                "owner_vs_replicated_eps", "overlap_ratio",
                "pipeline_depth",
                "num_samplers", "scaling_efficiency",
                "kge_steps_per_sec")

# serving headline keys (benchmarks/bench_serve.py -> SERVE.json);
# max_sustainable_qps_under_slo is the tracked capacity headline: the
# open-loop knee — the highest offered rate whose windowed p99 still
# clears the SLO target (ROADMAP item 2's "not latency at fixed qps")
SERVE_KEYS = ("qps", "p50_ms", "p95_ms", "p99_ms", "batch_occupancy",
              "requests", "batches", "max_sustainable_qps_under_slo")

# auto-tuning headline keys (benchmarks/bench_tune.py -> TUNE.json)
TUNE_KEYS = ("default_seeds_per_sec", "tuned_seeds_per_sec",
             "tuned_vs_default", "tuned_knobs", "probes_run",
             "rungs")

# hardware-utilization keys (obs/prof.py prof_summary -> PROF.json;
# `tpu-prof diff` gates on train_mfu + train_seeds_per_sec)
PROF_KEYS = ("train_mfu", "roofline_bound", "roofline_frac",
             "train_seeds_per_sec", "hbm_watermark_mib",
             "hbm_predicted_mib", "jit_compiles")

# model-health sentry overhead record (hack/quality_smoke.py ->
# benchmarks/QUALITY.json): sentry-on vs sentry-off throughput of the
# same seeded run, the overhead fraction, and the bit-identity verdict
# (ISSUE 15 acceptance — the sentry must not change the trajectory)
QUALITY_KEYS = ("sentry_on_seeds_per_sec", "sentry_off_seeds_per_sec",
                "sentry_overhead_frac", "bit_identical",
                "jit_compiles_on", "jit_compiles_off")

# communication-plane keys (obs/comm.py comm_summary ->
# benchmarks/COMM.json via benchmarks/bench_comm.py): per-op achieved
# bytes/seconds/GB/s from the per-collective ledger, the peak achieved
# link-utilization gauge, and the run's exchange/compute overlap —
# the network dimension of the roofline (ISSUE 19)
COMM_KEYS = ("comm_ops", "comm_bytes_total", "comm_seconds",
             "top_op", "top_op_gbps", "axis_util_max",
             "overlap_ratio")

# step-anatomy keys (obs/xray.py xray_summary ->
# benchmarks/XRAY.json via benchmarks/bench_xray.py): blame-attributed
# critical-path fractions per category (disjoint priority layering, so
# they sum to 1.0 by construction), the dominant critical-path owner,
# and the what-if estimates — "halo a2a free → step −18%" — the
# tpu-xray CLI and the doctor xray block render (ISSUE 20)
XRAY_KEYS = ("steps", "workers", "step_wall_mean_s",
             "critpath_frac_compute", "critpath_frac_comm",
             "critpath_frac_stall", "critpath_frac_ckpt",
             "critpath_frac_other", "critical_owner",
             "critical_owner_frac", "whatif_comm_free_frac",
             "whatif_stall_free_frac", "whatif_owner_at_median_frac",
             "periodic_spike_every")

# aggregation-kernel benchmark record (benchmarks/bench_kernels.py ->
# benchmarks/KERNELS.json, consumed by ops/dispatch.py): one entry per
# measured (rows, D, fanout) shape, each arm a STRUCTURED result —
# never a raw compiler-error string (the r3 KERNELS_TPU.json failure
# mode: multi-line HTTP-500 stderr with ANSI escapes as the value)
KERNEL_SHAPE_KEYS = ("rows", "D", "fanout")
KERNEL_RESULT_KEYS = ("rows", "D", "fanout", "xla", "pallas",
                      "recommendation")
KERNEL_TIMING_KEYS = ("status", "fanout_sum_us", "gather_rows_us")
KERNEL_ERROR_KEYS = ("status", "detail")
KERNEL_RECORD_KEYS = ("version", "platform", "pallas_mode",
                      "recommendation", "results")


def kernel_error_record(detail: str,
                        status: str = "compile_error") -> dict:
    """The structured failure entry a kernel-bench arm records when
    its executable cannot be built or run: ``{status, detail}`` with
    ``detail`` reduced to the FIRST line, ANSI escapes stripped and
    length-capped — a failing toolchain must never turn the tracked
    benchmark artifact into a log file."""
    import re
    text = re.sub(r"\x1b\[[0-9;]*[A-Za-z]", "", str(detail)).strip()
    first = text.splitlines()[0].strip() if text else ""
    return {"status": status, "detail": first[:200]}
