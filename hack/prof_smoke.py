"""`make prof` / `make prof-gate` smoke: hardware-utilization
introspection end to end (docs/profiling.md).

Five acts:

1. **Utilization telemetry** — a 2-part DistTrainer run on the virtual
   CPU mesh must leave nonzero ``train_mfu`` and per-device
   ``train_hbm_watermark_mib`` gauges in the job view, Chrome counter
   tracks (``MFU``, ``HBM MiB``) in ``job/trace.json``, and a
   "hardware" block in the tpu-doctor report — with NO steady-state
   recompile finding (the steady loop keeps one compiled shape per
   program, the runtime/loop.py padding invariant).
2. **Recompile detection** — a deliberately shape-churning jitted loop
   under ``instrument_jit`` must trigger the
   ``steady_state_recompile`` critical finding.
3. **Watermark drift** — a synthetic procs view with measured > 1.2x
   predicted HBM must produce the ``hbm_drift`` finding.
4. **Regression-gate rc contract** — ``tpu-prof diff run run`` exits
   0; an injected 20% step-rate/MFU regression against the same run
   under a 15% margin exits 1.
5. **Gate mode** (``PROF_GATE=1``, `make prof-gate`) — refresh or
   validate the tracked ``benchmarks/PROF.json`` and require
   ``tpu-prof diff <run> PROF.json`` to pass under the adoption
   margin (``PROF_GATE_MARGIN``, default 0.5 — CPU CI machines vary;
   calibrate down on pinned hardware, docs/profiling.md).

Usage:  python hack/prof_smoke.py            (CPU-only, ~40 s)
        PROF_GATE=1 python hack/prof_smoke.py    # + the CI gate
        PROF_UPDATE=1 PROF_GATE=1 ...            # rebase PROF.json
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="prof_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import doctor, get_obs  # noqa: E402
from dgl_operator_tpu.obs import prof as P  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh  # noqa: E402
from dgl_operator_tpu.runtime import DistTrainer, TrainConfig  # noqa: E402

PROF_RECORD = os.path.join(_REPO, "benchmarks", "PROF.json")


def act1_train_and_assert() -> dict:
    """2-part run -> job view must carry the full utilization story."""
    obs_dir = os.environ["TPU_OPERATOR_OBS_DIR"]
    ds = datasets.synthetic_node_clf(num_nodes=800, num_edges=4000,
                                     feat_dim=16, num_classes=4,
                                     seed=3)
    cfg_json = partition_graph(ds.graph, "prof", 2,
                               os.path.join(_TMP, "parts"))
    cfg = TrainConfig(num_epochs=2, batch_size=16, lr=0.01,
                      fanouts=(4, 4), log_every=10**9, eval_every=0,
                      feats_layout="owner", prefetch=2,
                      num_samplers=2)
    tr = DistTrainer(DistSAGE(hidden_feats=32, out_feats=4,
                              dropout=0.0), cfg_json,
                     make_mesh(num_dp=2), cfg)
    out = tr.train()
    get_obs().flush()

    report = doctor.build_report(obs_dir)
    hw = report.get("hardware")
    assert hw, "doctor report has no hardware-utilization block"
    assert hw["mfu"] and hw["mfu"] > 0, hw
    assert hw["hbm_watermark_mib"] and hw["hbm_watermark_mib"] > 0, hw
    assert hw["roofline_bound"] in ("compute", "memory", "comm"), hw
    assert hw["jit_compiles"] >= 1, hw
    kinds = {f["kind"] for f in report["findings"]}
    assert "steady_state_recompile" not in kinds, \
        f"steady loop flagged as recompiling: {kinds}"

    trace = json.load(open(os.path.join(obs_dir, "job", "trace.json")))
    counters = {e["name"] for e in trace["traceEvents"]
                if e.get("ph") == "C"}
    assert {"MFU", "HBM MiB"} <= counters, counters

    summary = P.prof_summary(obs_dir)
    assert summary is not None and summary["train_mfu"] > 0, summary
    assert summary["train_seeds_per_sec"] and \
        summary["train_seeds_per_sec"] > 0, summary
    print(f"act1: MFU {summary['train_mfu']:.4f} "
          f"({summary['roofline_bound']}-bound), HBM "
          f"{summary['hbm_watermark_mib']:.1f} MiB, "
          f"{summary['jit_compiles']} compile(s), steps {out['step']}")
    return summary


def act2_recompile_fires() -> None:
    """Shape churn past warmup must be a critical finding; the same
    loop on one shape must stay silent."""
    import jax
    import jax.numpy as jnp

    from dgl_operator_tpu.obs import obs_run
    from dgl_operator_tpu.obs.analyze import analyze_job, load_events

    def run_loop(obs_dir: str, churn: bool) -> dict:
        with obs_run(obs_dir, role="churn", console=False):
            fn = P.instrument_jit(
                "churn_step", jax.jit(lambda x: (x * 2.0).sum()),
                role="step")
            for i in range(6):
                n = 8 + (i if churn else 0)
                fn(jnp.ones((n,), jnp.float32)).block_until_ready()
            get_obs().flush()
        return analyze_job(events=load_events(
            os.path.join(obs_dir, "events.jsonl")))

    churn_rep = run_loop(os.path.join(_TMP, "churn_obs"), churn=True)
    churn = [f for f in churn_rep["findings"]
             if f["kind"] == "steady_state_recompile"]
    assert churn and churn[0]["severity"] == "critical", \
        churn_rep["findings"]
    steady_rep = run_loop(os.path.join(_TMP, "steady_obs"),
                          churn=False)
    assert not any(f["kind"] == "steady_state_recompile"
                   for f in steady_rep["findings"]), \
        steady_rep["findings"]
    n_steady = churn[0]["evidence"]["count"]
    print(f"act2: churn loop -> critical ({n_steady} steady "
          "recompiles); steady loop -> silent")


def act3_hbm_drift() -> None:
    from dgl_operator_tpu.obs.analyze import analyze_job
    procs = {"vm:1:trainer-0": {
        "train_hbm_watermark_mib": {"type": "gauge", "samples": [
            {"labels": {"device": "d0"}, "value": 150.0}]},
        "train_hbm_predicted_mib": {"type": "gauge", "samples": [
            {"labels": {}, "value": 100.0}]},
    }}
    rep = analyze_job(events=[], procs=procs)
    drift = [f for f in rep["findings"] if f["kind"] == "hbm_drift"]
    assert drift and drift[0]["severity"] == "warning", rep["findings"]
    print("act3: 50% watermark overshoot -> hbm_drift finding")


def act4_diff_rc_contract(summary: dict) -> None:
    run_json = os.path.join(_TMP, "prof_run.json")
    with open(run_json, "w") as f:
        json.dump(summary, f)
    rc = P.main(["diff", run_json, run_json])
    assert rc == 0, f"self-diff must pass, got rc {rc}"
    # inject a 20% step-rate (and MFU) regression; a 15% adoption
    # margin must catch it — the gate trips on a genuine regression
    injected = dict(summary)
    for key in P.GATED_KEYS:
        if injected.get(key):
            injected[key] = injected[key] * 0.8
    inj_json = os.path.join(_TMP, "prof_injected.json")
    with open(inj_json, "w") as f:
        json.dump(injected, f)
    rc = P.main(["diff", inj_json, run_json, "--margin", "0.15"])
    assert rc == 1, f"injected 20% regression must fail, got rc {rc}"
    print("act4: diff rc contract holds (self-pass, injected-fail)")


def act5_gate(summary: dict) -> None:
    """`make prof-gate`: validate the run against the tracked record
    under the adoption margin (wide by default — CPU CI machines
    differ; the injected-regression check in act 4 is what proves the
    gate's teeth deterministically)."""
    update = os.environ.get("PROF_UPDATE") == "1" \
        or not os.path.exists(PROF_RECORD)
    if update:
        rec = {"what": "hardware-utilization smoke record "
                       "(hack/prof_smoke.py, 2-part DistTrainer on "
                       "the virtual CPU mesh)",
               "ok": True,
               "host": {"cores": os.cpu_count()},
               "prof": summary}
        tmp = PROF_RECORD + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=2, sort_keys=True)
        os.replace(tmp, PROF_RECORD)
        print(f"act5: refreshed {os.path.relpath(PROF_RECORD, _REPO)}")
    run_json = os.path.join(_TMP, "prof_run.json")
    margin = os.environ.get("PROF_GATE_MARGIN", "0.5")
    rc = P.main(["diff", run_json, PROF_RECORD, "--margin", margin])
    assert rc == 0, \
        (f"prof gate failed: run regressed past margin {margin} vs "
         f"benchmarks/PROF.json (rc {rc}); rebase with PROF_UPDATE=1 "
         "if the baseline machine changed)")
    print(f"act5: gate passed vs tracked PROF.json (margin {margin})")


def main() -> None:
    try:
        summary = act1_train_and_assert()
        act2_recompile_fires()
        act3_hbm_drift()
        act4_diff_rc_contract(summary)
        if os.environ.get("PROF_GATE") == "1":
            act5_gate(summary)
        print(json.dumps({
            "metric": "prof_smoke", "ok": True,
            "mfu": summary["train_mfu"],
            "bound": summary["roofline_bound"],
            "hbm_watermark_mib": summary["hbm_watermark_mib"],
            "gated": os.environ.get("PROF_GATE") == "1"}))
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)


if __name__ == "__main__":
    main()
