"""`make zero` smoke: ZeRO-style rule-driven state sharding end to end
(docs/sharding.md).

A 2x2-mesh DistKGETrainer run under ``shard_rules`` must

1. hold per-slot relation-table + optimizer bytes strictly below the
   replicated baseline — checked BOTH analytically
   (``state_sharding_summary``) and against the real per-device buffer
   shards of the live arrays;
2. train a loss trajectory bit-identical to the replicated run;
3. resume bit-exactly from a sharded checkpoint after a mid-train
   kill: the first trainer stops at the half-way step (its checkpoint
   is the logical, mesh-shape-invariant state), a FRESH trainer
   resumes to the end, and the final tables equal the uninterrupted
   replicated run's exactly;
4. leave the ``train_state_mib_per_slot`` gauges in the obs metrics so
   ``tpu-doctor`` renders its "state sharding" block.

Usage:  python hack/shard_smoke.py        (CPU-only, ~30 s)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="shard_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

import numpy as np  # noqa: E402

from dgl_operator_tpu.graph.kge_sampler import TrainDataset  # noqa: E402
from dgl_operator_tpu.models.kge import KGEConfig  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.obs.doctor import build_report, render  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh_2d  # noqa: E402
from dgl_operator_tpu.runtime.kge import (DistKGETrainer,  # noqa: E402
                                          KGETrainConfig)

RULES = (("^relation$", "dp"), (".*", None))
STEPS = 20


def main() -> int:
    rng = np.random.default_rng(0)
    ne, nr = 400, 24
    h = rng.integers(0, ne, 4000)
    r = rng.integers(0, nr, 4000)
    t = rng.integers(0, ne, 4000)
    cfg = KGEConfig(model_name="ComplEx", n_entities=ne, n_relations=nr,
                    hidden_dim=16)

    def trainer(rules, max_step, ckpt=None):
        tcfg = KGETrainConfig(lr=0.5, max_step=max_step, batch_size=64,
                              neg_sample_size=8, neg_chunk_size=8,
                              seed=11, shard_rules=rules,
                              ckpt_dir=ckpt, ckpt_every=STEPS // 2)
        mesh = make_mesh_2d(2, 4)
        td = TrainDataset((h, r, t), ne, nr,
                          ranks=int(mesh.devices.size))
        return DistKGETrainer(cfg, tcfg, mesh), td

    # replicated baseline, uninterrupted
    tr_rep, td = trainer(None, STEPS)
    out_rep = tr_rep.train(td)
    p_rep = tr_rep.gathered_params()

    # sharded, killed at the half-way checkpoint, resumed fresh
    ckpt_dir = os.path.join(_TMP, "ckpt")
    tr_a, td_a = trainer(RULES, STEPS // 2, ckpt_dir)
    out_a = tr_a.train(td_a)        # "killed" right after its save
    tr_b, td_b = trainer(RULES, STEPS, ckpt_dir)
    out_b = tr_b.train(td_b)        # resumes from the sharded ckpt
    p_shd = tr_b.gathered_params()

    summary = out_b["state_sharding"]
    opt_ratio = (summary["opt_state_mib_per_slot_sharded"]
                 / max(summary["opt_state_mib_per_slot_replicated"],
                       1e-12))
    assert (summary["params_mib_per_slot_sharded"]
            < summary["params_mib_per_slot_replicated"]), summary
    assert (summary["opt_state_mib_per_slot_sharded"]
            < summary["opt_state_mib_per_slot_replicated"]), summary

    # the LIVE arrays agree with the analytic claim: each device
    # persists only a 1/dp row block of the relation table + state
    rel_shard = tr_b.relation.addressable_shards[0].data
    st_shard = tr_b.rel_state.addressable_shards[0].data
    assert rel_shard.shape[0] * 2 == tr_b.relation.shape[0], (
        rel_shard.shape, tr_b.relation.shape)
    assert st_shard.shape[0] * 2 == tr_b.rel_state.shape[0]

    # bit-identical math + exact resume
    assert np.array_equal(np.asarray(p_rep["relation"]),
                          np.asarray(p_shd["relation"])), \
        "sharded relation diverged from the replicated run"
    assert np.array_equal(np.asarray(p_rep["entity"]),
                          np.asarray(p_shd["entity"])), \
        "entity table diverged after sharded-checkpoint resume"

    # the doctor sees the state-sharding gauges in the job view
    obs = get_obs()
    obs.flush()
    report = build_report(os.environ["TPU_OPERATOR_OBS_DIR"])
    block = report.get("state_sharding")
    assert block, "doctor report has no state_sharding block"
    assert "kge" in block.get("roles", {}), block
    print(render(report))

    print(json.dumps({
        "metric": "shard_smoke",
        "steps": STEPS,
        "loss_replicated": out_rep["loss"],
        "loss_sharded": out_b["loss"],
        "resume_from": out_a["steps"],
        "opt_state_ratio": round(opt_ratio, 4),
        "state_savings_ratio": summary["state_savings_ratio"],
        "ok": True}))
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)
    sys.exit(rc)
