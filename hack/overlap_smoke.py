"""`make overlap` smoke — the ISSUE 14 fused-pipeline evidence, two
parts:

1. **In-program overlap**: a 2-part owner-layout run under
   ``pipeline_mode="fused"`` must leave Chrome-trace evidence that the
   halo collective executed INSIDE the step's program — the
   ``halo_exchange_fused`` spans (recorded by the step watcher for
   every step whose program issued the next batch's a2a) lie within /
   overlap the ``train_compute`` spans — and the run must report an
   ``overlap_ratio`` at least as good as the two-program staged
   baseline measured in the same process (the fused form hides the
   exchange by construction; the staged form leaves it to dispatch
   luck).

2. **Zero steady-state host round-trips**: a device-sampler run (the
   device-resident translator: in-step manifest translation + the
   epoch seed bank + index carry) must stage host payloads ONLY at
   epoch cadence — ``train_host_staging_transfers_total`` shows
   ``kind="epoch"`` entries equal to the epoch count and ZERO
   ``kind="step"`` entries — and must log no steady-state recompile
   (``jit_compile`` events with ``steady: true``).

Usage:  python hack/overlap_smoke.py        (CPU-only, ~40 s)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="overlap_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh  # noqa: E402
from dgl_operator_tpu.runtime import DistTrainer, TrainConfig  # noqa: E402


def spans(trace: dict, name: str):
    return [(e["ts"], e["ts"] + e["dur"])
            for e in trace.get("traceEvents", [])
            if e.get("ph") == "X" and e.get("name") == name]


def train(cfg_json, **kw):
    cfg = TrainConfig(num_epochs=2, batch_size=16, lr=0.01,
                      fanouts=(4, 4), log_every=10**9, eval_every=0,
                      **kw)
    tr = DistTrainer(DistSAGE(hidden_feats=32, out_feats=4,
                              dropout=0.0), cfg_json,
                     make_mesh(num_dp=2), cfg)
    return tr.train()


def staging_counts():
    fam = get_obs().metrics.snapshot().get(
        "train_host_staging_transfers_total") or {}
    out = {}
    for s in fam.get("samples", []):
        out[s.get("labels", {}).get("kind", "?")] = s["value"]
    return out


def main() -> None:
    try:
        ds = datasets.synthetic_node_clf(num_nodes=800, num_edges=4000,
                                         feat_dim=16, num_classes=4,
                                         seed=3)
        cfg_json = partition_graph(ds.graph, "ovl", 2,
                                   os.path.join(_TMP, "parts"))

        # -- part 1: fused in-program overlap vs the staged baseline
        staged = train(cfg_json, feats_layout="owner",
                       pipeline_mode="staged", prefetch=2,
                       num_samplers=2)
        fused = train(cfg_json, feats_layout="owner",
                      pipeline_mode="fused", pipeline_depth=2,
                      prefetch=2, num_samplers=2)
        assert [h["loss"] for h in fused["history"]] == \
            [h["loss"] for h in staged["history"]], "fused != staged"
        s_ratio = staged["history"][-1]["overlap_ratio"]
        f_ratio = fused["history"][-1]["overlap_ratio"]
        assert f_ratio >= s_ratio - 0.05, (f_ratio, s_ratio)
        get_obs().flush()
        trace = json.load(open(os.path.join(_TMP, "obs",
                                            "trace.json")))
        fx = spans(trace, "halo_exchange_fused")
        co = spans(trace, "train_compute")
        assert fx, "no in-program exchange spans recorded"
        # the in-program collective's window lies inside its step's
        # compute window by construction — every fused span must
        # overlap a compute span
        concurrent = sum(1 for a0, a1 in fx
                         if any(a0 < c1 and c0 < a1 for c0, c1 in co))
        assert concurrent == len(fx), (concurrent, len(fx))

        # -- part 2: device-resident translator, zero host round-trips
        before = staging_counts()
        dev = train(cfg_json, sampler="device")
        after = staging_counts()
        epochs = after.get("epoch", 0) - before.get("epoch", 0)
        steps = after.get("step", 0) - before.get("step", 0)
        assert epochs == 2, (before, after)
        assert steps == 0, (before, after)
        get_obs().flush()
        evs = [json.loads(ln) for ln in
               open(os.path.join(_TMP, "obs", "events.jsonl"))]
        steady = [e for e in evs if e.get("event") == "jit_compile"
                  and e.get("steady")]
        assert not steady, steady

        print(json.dumps({
            "metric": "overlap_smoke", "ok": True,
            "fused_overlap_ratio": f_ratio,
            "staged_overlap_ratio": s_ratio,
            "fused_exchange_spans": len(fx),
            "compute_spans": len(co),
            "device_epoch_stagings": epochs,
            "device_step_stagings": steps,
            "final_loss": round(dev["history"][-1]["loss"], 4)}))
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)


if __name__ == "__main__":
    main()
