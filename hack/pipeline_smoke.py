"""`make pipeline` smoke: a 2-part owner-layout DistTrainer run under
the TWO-PROGRAM async pipeline (``pipeline_mode="staged"`` — the PR 7
fallback kept explicitly testable, since it carries the
deterministic-dispatch hazard tpu-lint TPU002 encodes) must leave
Chrome-trace evidence that the staged halo exchange actually executed
CONCURRENT with compute — the ``halo_exchange`` spans (recorded by the
tpu-exchange worker) overlap the ``train_compute`` spans (recorded by
the step watcher) in ``trace.json`` — and the trainer must report a
non-trivial ``overlap_ratio`` for the same run
(runtime/timers.OverlapTracker). The FUSED in-program pipeline (the
ISSUE 14 hot path) has its own smoke: ``make overlap``
(hack/overlap_smoke.py).

Usage:  python hack/pipeline_smoke.py        (CPU-only, ~30 s)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# virtual-CPU-mesh rules shared with the test suite, plus a dedicated
# obs dir so the run's trace.json lands somewhere we can read —
# BEFORE any dgl_operator_tpu import touches the obs layer
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="pipeline_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh  # noqa: E402
from dgl_operator_tpu.runtime import DistTrainer, TrainConfig  # noqa: E402


def spans(trace: dict, name: str):
    """[(t0_us, t1_us), ...] of every complete span named ``name``."""
    return [(e["ts"], e["ts"] + e["dur"])
            for e in trace.get("traceEvents", [])
            if e.get("ph") == "X" and e.get("name") == name]


def main() -> None:
    try:
        ds = datasets.synthetic_node_clf(num_nodes=800, num_edges=4000,
                                         feat_dim=16, num_classes=4,
                                         seed=3)
        cfg_json = partition_graph(ds.graph, "pipe", 2,
                                   os.path.join(_TMP, "parts"))
        cfg = TrainConfig(num_epochs=2, batch_size=16, lr=0.01,
                          fanouts=(4, 4), log_every=10**9,
                          eval_every=0, feats_layout="owner",
                          pipeline_mode="staged",
                          prefetch=2, num_samplers=2)
        tr = DistTrainer(DistSAGE(hidden_feats=32, out_feats=4,
                                  dropout=0.0), cfg_json,
                         make_mesh(num_dp=2), cfg)
        out = tr.train()
        get_obs().flush()

        rec = out["history"][-1]
        assert "overlap_ratio" in rec, rec
        assert "stall" in rec or "sample" in rec, rec

        trace = json.load(open(os.path.join(_TMP, "obs", "trace.json")))
        ex = spans(trace, "halo_exchange")
        co = spans(trace, "train_compute")
        assert len(ex) >= out["step"] - 1, (len(ex), out["step"])
        assert len(co) >= out["step"] - 1, (len(co), out["step"])
        # the acceptance evidence: at least one staged exchange window
        # genuinely overlaps a compute window — concurrent rows, not
        # serialized stages
        concurrent = sum(
            1 for a0, a1 in ex
            if any(a0 < c1 and c0 < a1 for c0, c1 in co))
        assert concurrent > 0, "no exchange span overlapped compute"

        print(json.dumps({
            "metric": "pipeline_smoke", "ok": True,
            "steps": out["step"],
            "exchange_spans": len(ex),
            "compute_spans": len(co),
            "concurrent_exchange_spans": concurrent,
            "overlap_ratio": rec["overlap_ratio"],
            "final_loss": round(rec["loss"], 4)}))
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)


if __name__ == "__main__":
    main()
