"""`make obs` smoke: drive a 2-host LocalFabric tpurun job with chaos
enabled and assert the run's telemetry contract — ``events.jsonl``,
``metrics.prom`` and ``trace.json`` all exist under the workspace
``obs/`` directory, parse, and carry the injected faults / retries /
phase events the observability layer promises (docs/observability.md).

With ``OBS_SMOKE_DOCTOR=1`` (`make doctor`) the same run is then
diagnosed: the auto-collected job view (``obs/job/``) must exist and
``tpu-doctor`` must render a report carrying the faults and phases.

Usage:  python hack/obs_smoke.py        (CPU-only, ~1 min)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# tests and smoke drives share the virtual-CPU-mesh environment rules
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import tpurun  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 write_hostfile)

ENTRY = """
    import argparse, json, os
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig
    rank = os.environ.get("TPU_OPERATOR_RANK", "0")
    ds = datasets.synthetic_node_clf(num_nodes=300, num_edges=1500,
                                     feat_dim=8, num_classes=4, seed=3)
    cfg = TrainConfig(num_epochs=a.num_epochs, batch_size=a.batch_size,
                      fanouts=(3, 3), log_every=1000, eval_every=1000,
                      dropout=0.0)
    out = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                  dropout=0.0), ds.graph, cfg).train()
    with open(r"{result_dir}/result-" + rank + ".json", "w") as f:
        json.dump({{"step": out["step"]}}, f)
"""


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="obs_smoke_")
    try:
        ws = os.path.join(tmp, "ws")
        conf = os.path.join(tmp, "conf")
        os.makedirs(ws)
        os.makedirs(conf)
        g = datasets.karate_club().graph
        partition_graph(g, "karate", 2, os.path.join(ws, "dataset"))
        write_hostfile(os.path.join(conf, "hostfile"),
                       [HostEntry("10.0.0.0", 30050, "w0-worker", 1),
                        HostEntry("10.0.0.1", 30051, "w1-worker", 1)])
        entry = os.path.join(tmp, "train.py")
        with open(entry, "w") as f:
            f.write(textwrap.dedent(ENTRY.format(result_dir=tmp)))

        os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)   # Launcher mode
        os.environ["TPU_OPERATOR_CHAOS"] = \
            "exec:fail:1@host=w1-worker;copy:fail:1@host=w0-worker"
        os.environ["TPU_OPERATOR_RETRY_BASE_S"] = "0.05"
        tpurun.main(["--graph-name", "karate", "--num-partitions", "2",
                     "--train-entry-point", entry, "--workspace", ws,
                     "--conf-dir", conf, "--num-epochs", "1",
                     "--batch-size", "32", "--fabric", "local"])

        results = sorted(fn for fn in os.listdir(tmp)
                         if fn.startswith("result-"))
        assert results == ["result-0.json", "result-1.json"], results

        obs = os.path.join(ws, "obs")
        events = [json.loads(ln)
                  for ln in open(os.path.join(obs, "events.jsonl"))]
        kinds = [e["event"] for e in events]
        assert kinds.count("phase_finish") == 3, kinds
        assert kinds.count("chaos_fault") == 2, kinds
        assert "fabric_retry" in kinds and "epoch" in kinds, kinds

        prom = open(os.path.join(obs, "metrics.prom")).read()
        for metric in ("chaos_faults_injected_total",
                       "fabric_retries_total",
                       "fabric_host_failures_total",
                       "tpurun_phases_total", "train_epoch_seconds"):
            assert metric in prom, metric
        merged = json.load(
            open(os.path.join(obs, "metrics.json")))["merged"]
        assert merged["train_epochs_total"]["samples"][0]["value"] == 2

        trace = json.load(open(os.path.join(obs, "trace.json")))
        xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in xs} >= {
            "phase 3: dispatch partitions",
            "phase 5: launch the training"}
        assert len({e["pid"] for e in xs}) >= 3   # driver + 2 trainers

        # the driver auto-collects the job view after phase 5
        job_dir = os.path.join(obs, "job")
        assert os.path.isdir(job_dir), "obs/job/ not collected"
        assert "obs_collected" in kinds, kinds

        doctor_rc = None
        if os.environ.get("OBS_SMOKE_DOCTOR"):
            from dgl_operator_tpu.obs import doctor
            doctor_rc = doctor.main([obs])
            report = json.load(
                open(os.path.join(job_dir, "report.json")))
            # both faults can land in ONE copy_batch attempt -> a
            # single batch retry event covers them
            assert report["summary"]["retries"] >= 1
            assert len(report["summary"]["faults_injected"]) == 2
            kindset = {f["kind"] for f in report["findings"]}
            assert "fault_injected" in kindset, kindset
            assert doctor_rc == 0, doctor_rc   # no critical findings

        print(json.dumps({
            "metric": "obs_smoke", "ok": True,
            "events": len(events),
            "chaos_faults": kinds.count("chaos_fault"),
            "retries": kinds.count("fabric_retry"),
            "procs": len(json.load(
                open(os.path.join(obs, "metrics.json")))["procs"]),
            "trace_spans": len(xs),
            "doctor_rc": doctor_rc}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
