"""`make xray` smoke — the ISSUE 20 step-anatomy evidence, end to
end: a 2-host LocalFabric ``tpurun`` job with a chaos
``step:slow:<s>@host=w1-worker`` straggler drag on ONE host, then the
analyzer must reconstruct the cross-host step anatomy from the merged
job view and name that host:

1. **Attribution**: ``xray_summary`` over the run's obs dir names the
   dragged trainer (rank 1 = ``trainer-1``) as the critical-path
   owner, credits >= the injected drag to the ``stall`` category, and
   its per-category fractions sum to 1.0 +- 0.01.

2. **Doctor block**: ``tpu-doctor`` over the same dir renders the
   ``xray    :`` step-anatomy block and the straggler finding stays
   sub-critical (exit 0 — a dragged-but-alive host is a warning).

3. **CLI contract**: ``tpu-xray <obs>`` exits 0 and prints the owner;
   ``--json`` round-trips; an empty dir exits 1 (no step telemetry);
   a missing dir exits 2.

Usage:  python hack/xray_smoke.py        (CPU-only, ~1 min)
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import shutil
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import tpurun  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 write_hostfile)

_SLOW_S = 0.05

ENTRY = """
    import argparse, json, os
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig
    rank = os.environ.get("TPU_OPERATOR_RANK", "0")
    ds = datasets.synthetic_node_clf(num_nodes=300, num_edges=1500,
                                     feat_dim=8, num_classes=4, seed=3)
    cfg = TrainConfig(num_epochs=a.num_epochs, batch_size=a.batch_size,
                      fanouts=(3, 3), log_every=1000, eval_every=1000,
                      dropout=0.0)
    out = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                  dropout=0.0), ds.graph, cfg).train()
    with open(r"{result_dir}/result-" + rank + ".json", "w") as f:
        json.dump({{"step": out["step"]}}, f)
"""


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="xray_smoke_")
    try:
        ws = os.path.join(tmp, "ws")
        conf = os.path.join(tmp, "conf")
        os.makedirs(ws)
        os.makedirs(conf)
        g = datasets.karate_club().graph
        partition_graph(g, "karate", 2, os.path.join(ws, "dataset"))
        write_hostfile(os.path.join(conf, "hostfile"),
                       [HostEntry("10.0.0.0", 30050, "w0-worker", 1),
                        HostEntry("10.0.0.1", 30051, "w1-worker", 1)])
        entry = os.path.join(tmp, "train.py")
        with open(entry, "w") as f:
            f.write(textwrap.dedent(ENTRY.format(result_dir=tmp)))

        os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)   # Launcher mode
        # drag ONLY the second hostfile host — rank 1 / trainer-1
        os.environ["TPU_OPERATOR_CHAOS"] = \
            f"step:slow:{_SLOW_S}@host=w1-worker"
        os.environ["TPU_OPERATOR_RETRY_BASE_S"] = "0.05"
        try:
            tpurun.main(["--graph-name", "karate",
                         "--num-partitions", "2",
                         "--train-entry-point", entry,
                         "--workspace", ws, "--conf-dir", conf,
                         "--num-epochs", "2", "--batch-size", "32",
                         "--fabric", "local"])
        finally:
            os.environ.pop("TPU_OPERATOR_CHAOS", None)

        results = sorted(fn for fn in os.listdir(tmp)
                         if fn.startswith("result-"))
        assert results == ["result-0.json", "result-1.json"], results

        obs = os.path.join(ws, "obs")
        assert os.path.isdir(os.path.join(obs, "job")), \
            "obs/job/ not collected"
        events = [json.loads(ln)
                  for ln in open(os.path.join(obs, "events.jsonl"))]
        kinds = [e["event"] for e in events]
        assert "chaos_step_slow" in kinds, kinds

        # -- act 1: attribution names the dragged host ---------------
        from dgl_operator_tpu.obs.xray import CATEGORIES, xray_summary
        s = xray_summary(obs)
        assert s is not None, "no step telemetry in the merged view"
        assert s["workers"] == 2, s["workers"]
        owner = s["critical_owner"]
        assert owner and owner.endswith("trainer-1"), (
            f"critical-path owner {owner!r} is not the dragged "
            "w1-worker trainer")
        total = sum(s[f"critpath_frac_{c}"] for c in CATEGORIES)
        assert abs(total - 1.0) <= 0.01, (
            f"attribution fractions sum to {total:.4f}")
        injected = _SLOW_S * s["steps"] * s["critical_owner_frac"]
        stall_attr = s["owner_seconds"]["stall"]
        assert stall_attr >= injected * 0.95, (
            f"stall attribution {stall_attr:.3f}s < injected "
            f"{injected:.3f}s on the dragged host")
        assert s["whatif_stall_free_frac"] > 0, s

        # -- act 2: the doctor renders the step anatomy, rc 0 --------
        from dgl_operator_tpu.obs.doctor import main as doctor_main
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = doctor_main([obs])
        out = buf.getvalue()
        assert rc == 0, f"doctor rc {rc} on a dragged-but-alive run:\n{out}"
        assert "xray    :" in out, out
        assert "trainer-1" in out, out

        # -- act 3: the tpu-xray CLI contract ------------------------
        from dgl_operator_tpu.obs import xray
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert xray.main([obs]) == 0
        assert "trainer-1" in buf.getvalue()
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert xray.main([obs, "--json"]) == 0
        payload = json.loads(buf.getvalue())
        assert payload["critical_owner"] == owner, payload
        empty = os.path.join(tmp, "empty_obs")
        os.makedirs(empty)
        assert xray.main([empty]) == 1
        assert xray.main([os.path.join(tmp, "missing")]) == 2

        print(json.dumps({
            "metric": "xray_smoke", "ok": True,
            "steps": s["steps"],
            "critical_owner": owner,
            "critical_owner_frac": s["critical_owner_frac"],
            "stall_attr_s": round(stall_attr, 3),
            "injected_s": round(injected, 3),
            "whatif_stall_free_frac": s["whatif_stall_free_frac"],
            "doctor_rc": rc}))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
