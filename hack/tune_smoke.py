"""`make tune` smoke: the ISSUE 9 loop end to end on a tiny 2-part
graph — successive-halving search over {halo_cache_frac, num_samplers,
prefetch} emits a ``tuned.json`` manifest, a follow-up ``tpurun
--tuned-manifest`` job consumes it (the trainers' resolved config
carries the tuned knobs), and ``tpu-doctor`` over the job's obs view
reports the tuning block.

Usage:  python hack/tune_smoke.py        (CPU-only, ~2-3 min)
Env:    TUNE_SMOKE_N0=2  TUNE_SMOKE_STEPS=2   search size knobs
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# tests and smoke drives share the virtual-CPU-mesh environment rules
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

from dgl_operator_tpu.autotune import knobs as AK  # noqa: E402
from dgl_operator_tpu.autotune.probe import (ProbeSpec,  # noqa: E402
                                             make_probe_fn)
from dgl_operator_tpu.autotune.search import \
    successive_halving  # noqa: E402
from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import \
    partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import tpurun  # noqa: E402
from dgl_operator_tpu.obs import obs_run  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 write_hostfile)

# the consuming job's train entry: resolved knob values are written
# next to the result so the smoke can assert the manifest LANDED in
# the trainer's config (not merely in an env var)
ENTRY = """
    import argparse, json, os
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig
    from dgl_operator_tpu.runtime.loop import resolve_num_samplers
    rank = os.environ.get("TPU_OPERATOR_RANK", "0")
    ds = datasets.synthetic_node_clf(num_nodes=300, num_edges=1500,
                                     feat_dim=8, num_classes=4, seed=3)
    tr = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                 dropout=0.0), ds.graph,
                        TrainConfig(num_epochs=a.num_epochs,
                                    batch_size=a.batch_size,
                                    fanouts=(3, 3), log_every=1000,
                                    eval_every=1000, dropout=0.0))
    out = tr.train()
    with open(r"{result_dir}/result-" + rank + ".json", "w") as f:
        json.dump({{"step": out["step"],
                    "halo_cache_frac": tr.cfg.halo_cache_frac,
                    "prefetch": tr.cfg.prefetch,
                    "num_samplers": resolve_num_samplers(tr.cfg)}}, f)
"""


def main() -> None:
    n0 = int(os.environ.get("TUNE_SMOKE_N0", "2"))
    base_steps = int(os.environ.get("TUNE_SMOKE_STEPS", "2"))
    tmp = tempfile.mkdtemp(prefix="tune_smoke_")
    try:
        ws = os.path.join(tmp, "ws")
        conf = os.path.join(tmp, "conf")
        os.makedirs(ws)
        os.makedirs(conf)

        # ---- search: tiny 2-part graph, 2-rung successive halving
        ds = datasets.synthetic_node_clf(600, 3000, 16, 8, seed=7)
        probe_cfg = partition_graph(ds.graph, "tune", 2,
                                    os.path.join(tmp, "probe_parts"))
        space = {"halo_cache_frac": (0.0, 0.5),
                 "num_samplers": (1, 2),
                 "prefetch": (0, 2)}
        spec = ProbeSpec(part_config=probe_cfg, num_parts=2,
                         batch_size=32, fanouts=(3, 3), seed=0)
        with obs_run(os.path.join(ws, "obs"), role="tune-search"):
            result = successive_halving(
                space, make_probe_fn(spec, os.path.join(tmp, "probes")),
                n0=n0, eta=2, base_steps=base_steps, seed=0,
                ledger_path=os.path.join(ws, "tune_ledger.json"))
        assert len(result["schedule"]) >= 2, result["schedule"]
        manifest_path = os.path.join(ws, "tuned.json")
        AK.write_manifest(manifest_path, result["winner"],
                          score=result["winner_score"],
                          search={"signature": result["signature"]})
        man = AK.load_manifest(manifest_path)
        assert set(man["knobs"]) == set(space), man
        print(f"tune_smoke: manifest {manifest_path} -> "
              f"{man['knobs']} (score {result['winner_score']:.1f}, "
              f"{result['probes_run']} probes)")

        # ---- consume: a 2-host LocalFabric job under the manifest
        g = datasets.karate_club().graph
        partition_graph(g, "karate", 2, os.path.join(ws, "dataset"))
        write_hostfile(os.path.join(conf, "hostfile"),
                       [HostEntry("10.0.0.0", 30050, "w0-worker", 1),
                        HostEntry("10.0.0.1", 30051, "w1-worker", 1)])
        entry = os.path.join(tmp, "train.py")
        with open(entry, "w") as f:
            f.write(textwrap.dedent(ENTRY.format(result_dir=tmp)))
        os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)  # Launcher mode
        os.environ.pop(AK.TUNED_MANIFEST_ENV, None)
        tpurun.main(["--graph-name", "karate", "--num-partitions", "2",
                     "--train-entry-point", entry, "--workspace", ws,
                     "--conf-dir", conf, "--num-epochs", "1",
                     "--batch-size", "32", "--fabric", "local",
                     "--tuned-manifest", manifest_path])
        os.environ.pop(AK.TUNED_MANIFEST_ENV, None)

        # the knob values the trainers must have resolved (a winner
        # equal to the registry defaults applies no override — the
        # scores are wall-clock measurements, so either outcome is
        # legitimate here; the deterministic override path is pinned
        # by tests/test_autotune.py)
        expect_overrides = sorted(
            k for k, v in man["knobs"].items()
            if v != AK.default_of(k))
        for rank in ("0", "1"):
            with open(os.path.join(tmp, f"result-{rank}.json")) as f:
                res = json.load(f)
            for knob in ("halo_cache_frac", "prefetch"):
                if knob in man["knobs"]:
                    assert res[knob] == man["knobs"][knob], (knob, res)
            want_ns = man["knobs"].get("num_samplers")
            if want_ns:
                assert res["num_samplers"] == want_ns, res
        print("tune_smoke: both trainers resolved the tuned knobs "
              f"(manifest departs from defaults on: "
              f"{expect_overrides or 'nothing — defaults won'})")

        # ---- diagnose: the doctor reports the tuning block
        from dgl_operator_tpu.obs.doctor import build_report, render
        report = build_report(os.path.join(ws, "obs"))
        tn = report.get("tuning")
        assert tn, "doctor report carries no tuning block"
        assert tn["probes"].get("run", 0) + \
            tn["probes"].get("ledger_skip", 0) >= 3, tn
        assert sorted(tn["overrides_applied"]) == expect_overrides, tn
        assert tn["manifests_loaded"] >= 1, tn
        text = render(report)
        assert "tuning  :" in text, text
        print("tune_smoke: doctor tuning block OK")
        print("tune_smoke: PASS")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
