"""`make obs-live` smoke: the live observability plane end to end.

Three acts (docs/observability.md "Live monitoring"):

1. **Live trainer feed** — a 2-host LocalFabric `tpurun` job runs with
   the live sidecars enabled (the launcher exports
   ``TPU_OPERATOR_LIVE_PORT=0``); while phase 5 trains, a concurrent
   ``tpu-top --once`` against the workspace obs dir must render at
   least one LIVE trainer row (step + heartbeat rate served over a
   sidecar's /livez, not read from files).
2. **Cross-process trace** — the merged ``obs/job/trace.json`` must
   carry ONE trace id from the driver's `tpurun` root span through the
   phase-5 span into both trainers' `train` spans (≥ 3 processes).
3. **SLO breach → shedding** — a micro-batcher fronted by a
   chaos-delayed executor under a tight ``p99_ms`` target must flip to
   shedding (submit raises Overloaded, ``serve_requests_shed_total``
   counts it) and the breach must surface in the tpu-doctor report.

Usage:  python hack/obslive_smoke.py        (CPU-only, ~1 min)
"""

from __future__ import annotations

import io
import json
import os
import shutil
import sys
import tempfile
import textwrap
import threading
import time
from contextlib import redirect_stdout

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import tpurun  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 write_hostfile)

ENTRY = """
    import argparse, json, os
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig
    rank = os.environ.get("TPU_OPERATOR_RANK", "0")
    ds = datasets.synthetic_node_clf(num_nodes=400, num_edges=2000,
                                     feat_dim=8, num_classes=4, seed=3)
    cfg = TrainConfig(num_epochs=a.num_epochs, batch_size=a.batch_size,
                      fanouts=(3, 3), log_every=1000, eval_every=1000,
                      dropout=0.0)
    out = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                  dropout=0.0), ds.graph, cfg).train()
    with open(r"{result_dir}/result-" + rank + ".json", "w") as f:
        json.dump({{"step": out["step"]}}, f)
"""


def _top_once(obs_dir: str) -> str:
    from dgl_operator_tpu.obs import top
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = top.main(["--once", obs_dir])
    assert rc == 0, rc
    return buf.getvalue()


def _watch_top(obs_dir: str, out: dict, stop: threading.Event) -> None:
    """Poll tpu-top --once until a LIVE trainer row appears (the
    trainers only live for the duration of phase 5)."""
    while not stop.is_set():
        try:
            frame = _top_once(obs_dir)
        except Exception:   # obs dir may not exist yet
            time.sleep(0.2)
            continue
        if ":trainer-" in frame and " live " in frame + " ":
            for line in frame.splitlines():
                if ":trainer-" in line and "live" in line:
                    out.setdefault("frames", []).append(frame)
                    out["live_row"] = line
                    return
        time.sleep(0.2)


def run_job(tmp: str) -> str:
    ws = os.path.join(tmp, "ws")
    conf = os.path.join(tmp, "conf")
    os.makedirs(ws)
    os.makedirs(conf)
    g = datasets.karate_club().graph
    partition_graph(g, "karate", 2, os.path.join(ws, "dataset"))
    write_hostfile(os.path.join(conf, "hostfile"),
                   [HostEntry("10.0.0.0", 30050, "w0-worker", 1),
                    HostEntry("10.0.0.1", 30051, "w1-worker", 1)])
    entry = os.path.join(tmp, "train.py")
    with open(entry, "w") as f:
        f.write(textwrap.dedent(ENTRY.format(result_dir=tmp)))

    os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)   # Launcher mode
    os.environ.pop("TPU_OPERATOR_CHAOS", None)
    obs_dir = os.path.join(ws, "obs")
    watch: dict = {}
    stop = threading.Event()
    watcher = threading.Thread(target=_watch_top,
                               args=(obs_dir, watch, stop), daemon=True)
    watcher.start()
    try:
        tpurun.main(["--graph-name", "karate", "--num-partitions", "2",
                     "--train-entry-point", entry, "--workspace", ws,
                     "--conf-dir", conf, "--num-epochs", "3",
                     "--batch-size", "16", "--fabric", "local"])
    finally:
        stop.set()
        watcher.join(timeout=5)

    # act 1: tpu-top saw a live trainer row while the job ran
    assert watch.get("live_row"), \
        "tpu-top never rendered a live trainer row during phase 5"
    print("tpu-top live row:", watch["live_row"].strip())

    # act 2: one contiguous trace across >= 3 processes in the job view
    trace = json.load(open(os.path.join(obs_dir, "job", "trace.json")))
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"
             and isinstance(e.get("args"), dict)
             and e["args"].get("trace_id")]
    # anchor on the phase-5 span (the root `tpurun` span only closes
    # after collection ran, so it is not in the collected view yet —
    # its trace id rides every phase span's args regardless)
    p5 = [e for e in spans
          if e["name"] == "phase 5: launch the training"]
    assert p5, "phase-5 span missing from the job trace"
    tid = p5[0]["args"]["trace_id"]
    tied = [e for e in spans if e["args"]["trace_id"] == tid]
    names = {e["name"] for e in tied}
    pids = {e["pid"] for e in tied}
    assert sum(1 for e in tied if e["name"] == "train") >= 2, names
    assert len(pids) >= 3, f"trace spans only cover pids {pids}"
    print(f"trace {tid[:8]}…: {len(tied)} spans across "
          f"{len(pids)} processes")
    return obs_dir


def run_slo_shed(tmp: str) -> None:
    from dgl_operator_tpu.obs import doctor, init_obs
    from dgl_operator_tpu.obs.live import LiveFeed
    from dgl_operator_tpu.obs.slo import SLOMonitor
    from dgl_operator_tpu.serve.batcher import MicroBatcher, Overloaded

    obs_dir = os.path.join(tmp, "slo_obs")
    init_obs(obs_dir, role="serve", console=False)
    feed = LiveFeed(window_s=5.0)
    slo = SLOMonitor(targets={"p99_ms": 5.0}, window_s=5.0,
                     burn_threshold=0.5)

    def chaos_delay(seeds, seq):   # every request blows the 5ms SLO
        time.sleep(0.03)
        return seeds

    from dgl_operator_tpu.obs import get_obs
    b = MicroBatcher(chaos_delay, batch_size=4, max_wait_s=0.0)
    for i in range(6):
        b.submit([i])
        b.flush_now()
        slo_breaches = slo.evaluate(
            feed.snapshot(registry=get_obs().metrics))
    assert slo_breaches and slo_breaches[0]["target"] == "p99_ms", \
        slo_breaches
    b.set_shedding(True, reason="p99_ms breach")
    shed = 0
    for i in range(3):
        try:
            b.submit([i])
        except Overloaded:
            shed += 1
    assert shed == 3, shed
    get_obs().flush()

    report = doctor.build_report(obs_dir)
    kinds = {f["kind"] for f in report.get("findings", [])}
    assert "slo_breach" in kinds, kinds
    assert report["serve_slo"]["shed"] == 3, report["serve_slo"]
    assert report["serve_slo"]["slo_breaches"] >= 1
    print("slo breach -> shed: 3 requests rejected, doctor reports",
          sorted(kinds))


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="obslive_smoke_")
    try:
        obs_dir = run_job(tmp)
        run_slo_shed(tmp)
        print(json.dumps({"metric": "obslive_smoke", "ok": True,
                          "obs_dir_checked": bool(obs_dir)}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
