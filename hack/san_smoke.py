"""`make san` smoke: build the native layer under ASan+UBSan and
drive its two consumer surfaces through the sanitized artifacts —
the graphcore ctypes kernels (graph/_native.py) and the reconciler
JSON protocol (tpu-operator / tpu-watcher, controlplane/) — with
every report a hard failure (docs/static_analysis.md, sanitizer
section).

Two-stage by necessity: the Python interpreter is not ASan-
instrumented, so loading the sanitized ``libgraphcore.so`` via ctypes
needs ``LD_PRELOAD=libasan.so``. The parent stage builds
(``make -C dgl_operator_tpu/native sanitize``), resolves the runtime,
and re-execs itself; the child stage (SAN_SMOKE_CHILD=1) runs the
actual drives with ``DGL_TPU_NATIVE_LIB`` / the controlplane
``TPU_OPERATOR_NATIVE_BIN_DIR`` pointed at the san/ build, so the
UNCHANGED Python wrappers and Controller exercise the sanitized code.

Usage:  python hack/san_smoke.py        (CPU-only, ~30 s)
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

NATIVE = os.path.join(_REPO, "dgl_operator_tpu", "native")
SAN_LIB = os.path.join(NATIVE, "san", "libgraphcore.so")
SAN_BIN_DIR = os.path.join(NATIVE, "controlplane", "san")


def log(msg: str) -> None:
    print(f"[san_smoke] {msg}", flush=True)


# ---------------------------------------------------------------------
# stage 1 (plain python): build + re-exec under the ASan runtime
# ---------------------------------------------------------------------
def build_and_reexec() -> int:
    log("building sanitized native layer "
        "(make -C dgl_operator_tpu/native sanitize) ...")
    res = subprocess.run(["make", "-C", NATIVE, "sanitize"],
                         capture_output=True, text=True, timeout=600)
    if res.returncode != 0:
        log("FAIL: sanitize build failed:\n" + res.stderr[-4000:])
        return 1
    cxx = os.environ.get("CXX", "g++")
    asan = subprocess.run([cxx, "-print-file-name=libasan.so"],
                          capture_output=True, text=True,
                          timeout=60).stdout.strip()
    if not asan or not os.path.exists(asan):
        log(f"FAIL: could not resolve libasan.so via {cxx}")
        return 1
    env = dict(
        os.environ,
        SAN_SMOKE_CHILD="1",
        LD_PRELOAD=asan,
        # python "leaks" by design (interned objects live to exit);
        # everything else is a hard abort so a report cannot scroll by
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1",
        DGL_TPU_NATIVE_LIB=SAN_LIB,
        TPU_OPERATOR_NATIVE_BIN_DIR=SAN_BIN_DIR,
    )
    log(f"re-exec under LD_PRELOAD={asan}")
    return subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, timeout=600).returncode


# ---------------------------------------------------------------------
# stage 2 (ASan runtime preloaded): the drives
# ---------------------------------------------------------------------
def drive_graphcore() -> None:
    import numpy as np

    from dgl_operator_tpu.graph import _native

    assert _native.native_available(), "sanitized libgraphcore failed to load"
    loaded = getattr(_native._LIB, "_name", "")
    assert os.sep + "san" + os.sep in loaded, (
        f"loaded {loaded!r}, not the sanitized build")
    log(f"graphcore: driving ctypes kernels from {loaded}")

    rng = np.random.default_rng(0)
    n, ne = 400, 3000
    rows = rng.integers(0, n, ne).astype(np.int32)
    cols = rng.integers(0, n, ne).astype(np.int32)

    # build_csr: counting sort postconditions + numpy parity
    indptr, indices, eids = _native.build_csr(rows, cols, n)
    assert indptr[0] == 0 and indptr[-1] == ne
    assert np.all(np.diff(indptr) >= 0)
    perm = np.argsort(rows, kind="stable")
    assert np.array_equal(eids, perm)
    assert np.array_equal(indices, cols[perm])

    # sample_fanout: in-range picks, -1 padding, junk seeds tolerated
    seeds = np.concatenate([rng.integers(0, n, 64),
                            [-1, n + 5]]).astype(np.int64)
    nbr, nbr_eid = _native.sample_fanout(indptr, indices, eids, seeds,
                                         fanout=7, seed=123)
    assert nbr.shape == (len(seeds), 7)
    assert np.all(nbr[-2:] == -1) and np.all(nbr_eid[-2:] == -1)
    for i, s in enumerate(seeds[:-2]):
        row = nbr[i][nbr[i] >= 0]
        legal = indices[indptr[s]:indptr[s + 1]]
        assert np.all(np.isin(row, legal))

    # compact_frontier: sorted-unique append, capped respill
    frontier = np.arange(10, dtype=np.int64)
    for cap in (None, 16):
        src, pos, mask = _native.compact_frontier(frontier, nbr, cap, 7)
        assert np.array_equal(src[:10], frontier)
        tail = src[10:]
        assert np.all(np.diff(tail) > 0)       # sorted unique
        if cap is not None:
            assert len(src) <= cap
        live = mask.reshape(-1) > 0
        assert np.all(pos.reshape(-1)[live] < len(src))

    # greedy_partition: normal, single-part, and the empty-graph edge
    # (previously modulo-by-zero UB — pinned fixed here)
    parts = _native.greedy_partition(indptr, indices, 4, seed=9)
    assert parts.shape == (n,) and set(np.unique(parts)) <= set(range(4))
    one = _native.greedy_partition(indptr, indices, 1, seed=9)
    assert np.all(one == 0)
    empty = _native.greedy_partition(np.zeros(1, np.int64),
                                     np.empty(0, np.int32), 4, seed=9)
    assert empty.shape == (0,)

    # hem_coarsen: mass conservation through one contraction level
    m = 40
    u = rng.integers(0, m, 200).astype(np.int32)
    v = rng.integers(0, m, 200).astype(np.int32)
    keep = u != v                      # drop input self-loops for the
    u, v = u[keep], v[keep]            # weight-conservation check
    w = rng.random(len(u)).astype(np.float32) + 0.1
    vw = np.ones(m, np.float32)
    coarse_id, nc, cu, cv, cw, cvw = _native.hem_coarsen(u, v, w, vw, m,
                                                         seed=3)
    assert 0 < nc <= m and np.all(coarse_id >= 0) and np.all(coarse_id < nc)
    assert abs(float(cvw.sum()) - m) < 1e-3      # vertex mass exact
    # edge mass: coarse cut edges + contracted self-loops == total
    self_mass = float(w[coarse_id[u] == coarse_id[v]].sum())
    assert abs(float(cw.sum()) + self_mass - float(w.sum())) < 1e-2
    assert np.all(cu < cv)                        # each pair once

    # refine_boundary: a planted 2-block graph scrambled 20% must not
    # get worse, and capacities must hold
    blocks = (np.arange(m) >= m // 2).astype(np.int32)
    intra = blocks[u] == blocks[v]
    w2 = np.where(intra, 1.0, 0.05).astype(np.float32)
    parts0 = blocks.copy()
    flip = rng.random(m) < 0.2
    parts0[flip] = 1 - parts0[flip]

    def cut(p):
        return float(w2[p[u] != p[v]].sum())

    refined = _native.refine_boundary(u, v, w2, vw, m, 2,
                                      cap=m * 0.75, iters=4,
                                      parts=parts0)
    assert cut(refined) <= cut(parts0) + 1e-6
    assert max(np.bincount(refined, minlength=2)) <= m * 0.75 + 1e-6
    log("graphcore: all ctypes kernel drives clean under ASan+UBSan")


def drive_reconciler(tmp: str) -> None:
    from dgl_operator_tpu.controlplane import (Controller, FakeCluster,
                                               simple_job)
    from dgl_operator_tpu.controlplane.controller import (
        operator_binary, watcher_binary)

    opb = operator_binary()
    assert os.sep + "san" + os.sep in opb, opb
    log(f"reconciler: driving the JSON protocol through {opb}")

    # version + malformed-state handling (parser error paths: stod
    # overflow, trailing junk, bad escapes — rc 2, never a crash)
    out = subprocess.run([opb, "version"], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 0 and out.stdout.strip()
    for bad in ("", "{", '{"a": 1e99999}', '{"a": }', '{"a": "\\x"}',
                '{"a": 1} trailing', '[1,2', '"unterminated'):
        res = subprocess.run([opb, "reconcile"], input=bad,
                             capture_output=True, text=True, timeout=60)
        assert res.returncode == 2, (bad, res.returncode, res.stderr)

    # exotic-but-valid JSON round-trips the parser/dumper cleanly
    state = {"job": None, "configMap": None, "pods": [],
             "notes": "esc \\ \" é 世 \n\t", "nums":
             [0, -1, 3.5, 1e-3, 123456789012345.0]}
    res = subprocess.run([opb, "reconcile"], input=json.dumps(state),
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    json.loads(res.stdout)

    # the full controller e2e (test_controlplane.py sequence) against
    # the sanitized binary: Partitioning -> Partitioned -> Training ->
    # Completed exercises every action/status edge of the protocol
    cluster = FakeCluster(status_dir=os.path.join(tmp, "podstatus"))
    ctl = Controller(cluster)
    job = simple_job("sanjob", 2)
    ctl.reconcile(job)
    cluster.set_pod_phase("sanjob-partitioner", "Running")
    assert ctl.reconcile_until(job, "Partitioning") == "Partitioning"
    cluster.set_pod_phase("sanjob-partitioner", "Succeeded")
    assert ctl.reconcile_until(job, "Partitioned") == "Partitioned"
    ctl.reconcile(job)
    cluster.set_pod_phase("sanjob-worker-0", "Running")
    cluster.set_pod_phase("sanjob-worker-1", "Running")
    cluster.set_pod_phase("sanjob-launcher", "Running")
    assert ctl.reconcile_until(job, "Training") == "Training"
    cluster.set_pod_phase("sanjob-launcher", "Succeeded")
    assert ctl.reconcile_until(job, "Completed") == "Completed"
    log("reconciler: version/error-path/e2e protocol clean")

    # watcher barrier under sanitizers: opens on Running, fails fast
    # on a Failed pod, times out loudly
    wb = watcher_binary()
    wf = os.path.join(tmp, "watchfile")
    sd = os.path.join(tmp, "status")
    os.makedirs(sd, exist_ok=True)
    with open(wf, "w") as f:
        f.write("10.0.0.1 30050 pod-a slots=1\n"
                "10.0.0.2 30050 pod-b slots=1\n")
    for pod, phase in (("pod-a", "Running"), ("pod-b", "Pending")):
        with open(os.path.join(sd, pod), "w") as f:
            f.write(phase)
    proc = subprocess.Popen(
        [wb, "--watch-file", wf, "--status-dir", sd, "--mode", "ready",
         "--poll-ms", "50", "--timeout-ms", "20000"])
    time.sleep(0.3)
    with open(os.path.join(sd, "pod-b"), "w") as f:
        f.write("Running")
    assert proc.wait(timeout=60) == 0
    with open(os.path.join(sd, "pod-b"), "w") as f:
        f.write("Failed")
    res = subprocess.run(
        [wb, "--watch-file", wf, "--status-dir", sd, "--mode",
         "finished", "--poll-ms", "50", "--timeout-ms", "5000"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 1 and "Failed" in res.stderr
    log("watcher: barrier open/fail paths clean")


def child_main() -> int:
    tmp = tempfile.mkdtemp(prefix="san_smoke_")
    try:
        drive_graphcore()
        drive_reconciler(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    log("OK: native layer clean under ASan+UBSan")
    return 0


if __name__ == "__main__":
    if os.environ.get("SAN_SMOKE_CHILD"):
        sys.exit(child_main())
    sys.exit(build_and_reexec())
