"""`make comm` smoke — the ISSUE 19 communication-plane evidence,
three acts:

1. **Per-collective telemetry**: a 2-part owner-layout pipelined run
   plus a zero-3 run in the same obs dir must leave ``cat="comm"``
   Chrome spans for >= 3 distinct collective op kinds (the trace-time
   ledger seams: halo exchange, grad allreduce/reduce-scatter, param
   all-gather), nonzero ``comm_bytes_total{op,axis}`` /
   ``comm_seconds{op,axis}`` counters for each, and achieved-vs-peak
   link-utilization gauges (``comm_link_util`` > 0 against the comm
   knob layer's resolved ICI/DCN peaks).

2. **Doctor comm block**: ``tpu-doctor`` over that obs dir renders the
   ``comm :`` roofline block (pinned ``benchkeys.COMM_KEYS`` shape)
   and exits 0 — a healthy run with comm telemetry is not a finding.

3. **Flight recorder**: a child process chaos-killed by ``host:die``
   (``os._exit``, no unwinding — the worst-case death) must leave a
   crash-safe ``flight-<pid>.json`` dump whose ring carries comm
   samples, and ``tpu-doctor`` over THAT obs dir renders the incident
   timeline naming the collective in flight (exit 1: an unreplaced
   dead host is rightly critical).

Usage:  python hack/comm_smoke.py        (CPU-only, ~60 s)
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_CHILD = "--child" in sys.argv
if _CHILD:
    _TMP = os.environ["COMM_SMOKE_TMP"]   # parent owns the tree
else:
    _TMP = tempfile.mkdtemp(prefix="comm_smoke_")
    os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh  # noqa: E402
from dgl_operator_tpu.runtime import DistTrainer, TrainConfig  # noqa: E402


def train(cfg_json, **kw):
    cfg = TrainConfig(num_epochs=2, batch_size=16, lr=0.01,
                      fanouts=(4, 4), log_every=10**9, eval_every=0,
                      **kw)
    tr = DistTrainer(DistSAGE(hidden_feats=32, out_feats=4,
                              dropout=0.0), cfg_json,
                     make_mesh(num_dp=2), cfg)
    return tr.train()


def child() -> int:
    """The chaos victim: an owner-layout run the ``host:die`` rule
    hard-exits mid-train — the flight dump is the only artifact the
    parent asserts on (``os._exit`` skips every flush)."""
    ds = datasets.synthetic_node_clf(num_nodes=800, num_edges=4000,
                                     feat_dim=16, num_classes=4,
                                     seed=3)
    cfg_json = partition_graph(ds.graph, "commchaos", 2,
                               os.path.join(_TMP, "chaos_parts"))
    train(cfg_json, feats_layout="owner", pipeline_mode="staged",
          prefetch=2, num_samplers=2)
    return 1       # unreachable when the chaos rule fired


def doctor_run(obs_dir):
    from dgl_operator_tpu.obs.doctor import main as doctor_main
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = doctor_main([obs_dir])
    return rc, buf.getvalue()


def comm_samples(name):
    fam = get_obs().metrics.snapshot().get(name) or {}
    return {(s["labels"].get("op"), s["labels"].get("axis")):
            s.get("value") for s in fam.get("samples", [])}


def main() -> int:
    obs_dir = os.path.join(_TMP, "obs")
    ds = datasets.synthetic_node_clf(num_nodes=800, num_edges=4000,
                                     feat_dim=16, num_classes=4,
                                     seed=3)
    cfg_json = partition_graph(ds.graph, "comm", 2,
                               os.path.join(_TMP, "parts"))

    # -- act 1: the two arms leave >= 3 distinct collective kinds
    train(cfg_json, feats_layout="owner", pipeline_mode="staged",
          prefetch=2, num_samplers=2)
    train(cfg_json, zero_stage=3)
    get_obs().flush()

    byts = comm_samples("comm_bytes_total")
    secs = comm_samples("comm_seconds")
    ops = sorted({op for op, _ in byts})
    assert len(ops) >= 3, f"expected >=3 collective kinds, got {ops}"
    for key, v in byts.items():
        assert v and v > 0, (key, v)
        assert secs.get(key, 0) > 0, (key, secs)
    util = comm_samples("comm_link_util")
    assert util and all(v > 0 for v in util.values()), util

    with open(os.path.join(obs_dir, "trace.json")) as f:
        trace = json.load(f)
    span_ops = sorted({e["name"] for e in trace.get("traceEvents", [])
                       if e.get("ph") == "X"
                       and e.get("cat") == "comm"})
    assert len(span_ops) >= 3, f"comm spans only for {span_ops}"
    assert set(span_ops) <= set(ops), (span_ops, ops)

    # -- act 2: the doctor renders the comm roofline block, rc 0
    rc, out = doctor_run(obs_dir)
    assert rc == 0, f"doctor rc {rc} on a healthy comm run:\n{out}"
    assert "comm    :" in out, out
    assert any(f"{op}@" in out for op in ops), out

    # -- act 3: chaos host:die leaves the black box
    chaos_obs = os.path.join(_TMP, "chaos_obs")
    env = dict(os.environ, TPU_OPERATOR_OBS_DIR=chaos_obs,
               COMM_SMOKE_TMP=_TMP, TPU_OPERATOR_CHAOS="host:die:3")
    p = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--child"], env=env, capture_output=True,
                       text=True, timeout=600)
    assert p.returncode == 113, (p.returncode, p.stderr[-2000:])
    from dgl_operator_tpu.obs.flight import load_flights
    dumps = load_flights(chaos_obs)
    assert len(dumps) == 1, [d.get("reason") for d in dumps]
    dump = dumps[0]
    assert dump["reason"] == "host_died", dump["reason"]
    comm_notes = [s for s in dump["samples"] if s.get("kind") == "comm"]
    assert comm_notes, "flight ring carried no comm samples"
    named = dump.get("inflight") or dump.get("last_comm")
    assert named and named.get("op"), dump
    rc2, out2 = doctor_run(chaos_obs)
    assert rc2 == 1, f"unreplaced dead host must be critical:\n{out2}"
    assert "flight  :" in out2 and "host_died on" in out2, out2
    assert named["op"] in out2, (named, out2)

    print(json.dumps({
        "metric": "comm_smoke", "ok": True,
        "collective_kinds": ops,
        "comm_span_kinds": span_ops,
        "comm_bytes_total": round(sum(byts.values()), 1),
        "link_util_max": round(max(util.values()), 6),
        "flight_reason": dump["reason"],
        "flight_named_op": named["op"],
        "doctor_rc": rc}))
    return 0


if __name__ == "__main__":
    if _CHILD:
        sys.exit(child())
    try:
        rc = main()
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)
    sys.exit(rc)
