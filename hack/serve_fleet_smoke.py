"""`make serve-fleet` smoke: the replicated serving plane end to end.

The ISSUE 18 acceptance drill on a toy graph (CPU-only, ~2 min):

1. partition + briefly train, then boot THREE ServingPlane replicas
   behind a FleetRouter + RouterPlane — the fleet's single public
   endpoint;
2. fire concurrent load through the router while a ``replica:die``
   chaos rule hard-kills the replica owning the loaded partition
   mid-request: every client call must still answer 200 (the router
   retries the broken in-flight forward on a survivor — zero drops),
   and the probe loop must drain the dead replica;
3. regrow: a fresh plane under the same ring name readmits through the
   health probes and takes traffic again;
4. canary a ``promote:bad``-poisoned candidate checkpoint: the staged
   export is checksum-clean but NaN-poisoned, so only the canary's
   quality detectors (non-finite sentry + divergence vs the incumbent)
   can catch it — the verdict must roll back automatically with the
   incumbent still serving;
5. promote a CLEAN candidate through the same machinery (fence epoch
   advances, candidate rolls out fleet-wide);
6. run tpu-doctor over the finished run and assert the fleet block
   tells the whole story (replica down/regrown, rollback + promote).

Usage:  python hack/serve_fleet_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs, obs_run  # noqa: E402

REPLICAS = ("r0", "r1", "r2")


def _post(url, nodes, timeout=60):
    req = urllib.request.Request(
        url + "/predict", data=json.dumps({"nodes": nodes}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, {}


def main() -> None:
    import jax

    from dgl_operator_tpu.parallel import make_mesh
    from dgl_operator_tpu.runtime import DistTrainer, TrainConfig
    from dgl_operator_tpu.runtime.checkpoint import (ServingPromotion,
                                                     promotion_history,
                                                     read_fence)
    from dgl_operator_tpu.serve.engine import ServeConfig, ServeEngine
    from dgl_operator_tpu.serve.router import (CanaryController,
                                               FleetRouter, HashRing,
                                               Replica, RouterPlane)
    from dgl_operator_tpu.serve.server import ServingPlane

    tmp = tempfile.mkdtemp(prefix="serve_fleet_smoke_")
    obs_dir = os.path.join(tmp, "obs")

    # the ring is deterministic in the replica names, so the victim —
    # whoever owns part-0, where the drill sends its load — is known
    # before any plane boots; the chaos rule kills exactly that one
    victim = HashRing(REPLICAS).candidates("part-0")[0]
    os.environ["TPU_OPERATOR_CHAOS"] = f"replica:die:10@host={victim}"

    with obs_run(obs_dir, role="fleet-smoke"):
        ds = datasets.synthetic_node_clf(num_nodes=600, num_edges=3000,
                                         feat_dim=16, num_classes=4,
                                         seed=3)
        cfg_json = partition_graph(ds.graph, "smoke", 4,
                                   os.path.join(tmp, "parts"))
        model = DistSAGE(hidden_feats=16, out_feats=4, dropout=0.0)
        tcfg = TrainConfig(num_epochs=1, batch_size=16, lr=0.01,
                           fanouts=(3, 3), log_every=1000, eval_every=0,
                           cap_policy="worst")
        tr = DistTrainer(model, cfg_json, make_mesh(num_dp=4), tcfg)
        params = jax.device_get(tr.train()["params"])

        def boot(name):
            scfg = ServeConfig(fanouts=(3, 3), batch_size=16,
                               cap_policy="worst", max_wait_ms=1.0)
            eng = ServeEngine(model, cfg_json, params=params, cfg=scfg)
            return ServingPlane(eng, port=0, slo_interval_s=0,
                                name=name).start()

        planes = {n: boot(n) for n in REPLICAS}
        node_map = np.asarray(planes["r0"].engine.node_map)
        part0 = np.flatnonzero(node_map == 0)
        router = FleetRouter(
            [Replica(n, "127.0.0.1", p.port, plane=p)
             for n, p in planes.items()],
            node_map=node_map, probe_timeout_s=1.0)
        front = RouterPlane(router, port=0).start(probe_interval_s=0.2)
        url = f"http://127.0.0.1:{front.port}"
        try:
            # ---- phase 1: kill one replica under concurrent load ----
            statuses, lock = [], threading.Lock()

            def worker(w):
                rng = np.random.default_rng(100 + w)
                for _ in range(8):
                    # part-0 first seed pins the arc the victim owns
                    ids = [int(v) for v in
                           rng.choice(part0, size=2, replace=False)]
                    code, payload = _post(url, ids)
                    with lock:
                        statuses.append(code)
                    assert code != 200 or len(
                        payload["predictions"]) == len(ids)

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(statuses) == 32, "a client request was lost"
            # zero dropped in-flight requests: the die-triggering
            # forward retried on a survivor, so the client saw only
            # 200s (503s would be survivors shedding — none here)
            bad = [c for c in statuses if c != 200]
            assert not bad, f"non-200s under replica death: {bad}"
            assert planes[victim].dead, \
                f"chaos never killed {victim} (load miscounted?)"
            deadline = time.monotonic() + 20.0
            while (router.replica(victim).state != "down"
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert router.replica(victim).state == "down", \
                "probe loop never drained the dead replica"
            assert router.replicas_up() == 2
            code, _ = _post(url, [int(part0[0])])
            assert code == 200, "survivors stopped answering"
            print(f"fleet smoke: {victim} died under load, "
                  f"{len(statuses)} requests all 200, drained to "
                  f"{router.replicas_up()} survivors")

            # ---- phase 2: regrow under the same ring name ----------
            os.environ["TPU_OPERATOR_CHAOS"] = "promote:bad"
            reborn = boot(victim)
            planes[victim] = reborn
            rep = router.replica(victim)
            rep.port, rep.plane = reborn.port, reborn
            deadline = time.monotonic() + 20.0
            while (rep.state != "up"
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert rep.state == "up" and router.replicas_up() == 3, \
                "regrown replica never readmitted"
            print(f"fleet smoke: {victim} regrown, fleet back to "
                  f"{router.replicas_up()}")

            # ---- phase 3: poisoned canary must roll back -----------
            owner = router.ring.candidates("part-0")[0]
            canary_name = next(n for n in REPLICAS if n != owner)
            promo = ServingPromotion(os.path.join(tmp, "promo"))
            canary = CanaryController(router, promo, frac=0.5,
                                      divergence_threshold=0.95,
                                      min_mirrors=6)
            cand = promo.stage(params)      # promote:bad poisons here
            os.environ.pop("TPU_OPERATOR_CHAOS", None)
            canary.start(cand, replica=canary_name)
            sent = 0
            while canary.active and sent < 60:
                code, _ = _post(url, [int(part0[2 * (sent % 8)])])
                assert code == 200, "incumbent blinked during canary"
                sent += 1
            assert canary.verdict == "rollback", \
                f"poisoned candidate got verdict {canary.verdict!r}"
            assert promotion_history(promo.directory)[-1]["action"] \
                == "rolled_back"
            assert read_fence(promo.directory) is None, \
                "rollback must not advance the fence"
            code, _ = _post(url, [int(part0[0])])
            assert code == 200, "incumbent not serving after rollback"
            print("fleet smoke: poisoned candidate rolled back after "
                  f"{canary.mirrored} mirrors, incumbent serving")

            # ---- phase 4: clean candidate promotes ----------------
            cand2 = promo.stage(params)
            canary.start(cand2, replica=canary_name)
            sent = 0
            while canary.active and sent < 60:
                code, _ = _post(url, [int(part0[2 * (sent % 8)])])
                assert code == 200
                sent += 1
            assert canary.verdict == "promote", \
                f"clean candidate got verdict {canary.verdict!r}"
            fence = read_fence(promo.directory)
            assert fence and fence["epoch"] == 1
            print("fleet smoke: clean candidate promoted to epoch "
                  f"{fence['epoch']}")
        finally:
            front.stop()
            for p in planes.values():
                try:
                    p.stop()
                except Exception:  # noqa: BLE001 — dead planes half-stopped
                    pass
        get_obs().flush()

    # ---- phase 5: the doctor tells the story ----------------------
    from dgl_operator_tpu.obs.doctor import build_report, render

    report = build_report(obs_dir)
    fleet = report.get("serve_fleet")
    assert fleet, "doctor missed the fleet (serve_fleet block absent)"
    assert fleet["replicas_up"] == 3
    assert fleet["failovers"] >= 1 and fleet["retries"] >= 1
    assert fleet["replica_downs"] >= 1 and fleet["replica_regrows"] >= 1
    assert fleet["promoted"] == 1 and fleet["rolled_back"] == 1
    verdicts = [v["verdict"] for v in fleet["canary_verdicts"]]
    assert verdicts == ["rollback", "promote"], verdicts
    text = render(report)
    assert "fleet" in text and "rolled back" in text
    print(text)
    print("serve fleet smoke OK:", json.dumps(
        {k: fleet[k] for k in ("per_replica", "failovers", "retries",
                               "promoted", "rolled_back",
                               "replica_downs", "replica_regrows")}))


if __name__ == "__main__":
    main()
