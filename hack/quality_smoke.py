"""`make quality` smoke: the model-health plane end to end
(docs/observability.md "Model health", ISSUE 15).

Acts:
1. sentry overhead + bit-exactness — the same seeded SampledTrainer
   run with the numerics sentry OFF and ON must produce bit-identical
   final params with the SAME number of XLA compiles (the stats
   pytree must not add a recompile); the measured throughput pair is
   the overhead record (``benchmarks/QUALITY.json``, refreshed with
   ``QUALITY_UPDATE=1``);
2. chaos ``numerics:nan`` end to end — a 2-partition LocalFabric job
   under ``tpurun`` where the chaos plan poisons params mid-train:
   every trainer's sentry must detect the non-finite gradients, halt
   cleanly at the step boundary, quarantine the post-fault
   checkpoints, and the driver must roll back to the last-known-good
   checkpoint and COMPLETE with every partition's params bit-equal to
   an undisturbed same-seed run;
3. ``tpu-doctor`` must render the model-health block and report a
   ``numerics_fault`` finding naming the bad step and partition — as
   a WARNING (the rollback handled it), rc 0.

Usage:  python hack/quality_smoke.py        (CPU-only, ~1 min)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import tempfile
import textwrap
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import chaos, tpurun  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 write_hostfile)

NUM_PARTS = 2
EPOCHS = 2
BATCH = 16
OVERHEAD_EPOCHS = 6   # act-1 warm-epoch protocol (epoch 0 = compile)

ENTRY = """
    import argparse, hashlib, json, os
    import numpy as np
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    import jax
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.obs.quality import NUMERICS_FAULT_EXIT
    from dgl_operator_tpu.runtime import (NumericsFault, Preempted,
                                          SampledTrainer, TrainConfig)
    part = int(os.environ["TPU_OPERATOR_RANK"])
    ws = os.environ["TPU_OPERATOR_WORKSPACE"]
    ds = datasets.synthetic_node_clf(num_nodes=240, num_edges=1200,
                                     feat_dim=8, num_classes=4, seed=3)
    ids = np.nonzero(ds.graph.ndata["train_mask"])[0]
    cfg = TrainConfig(num_epochs=a.num_epochs, batch_size=a.batch_size,
                      fanouts=(3, 3), log_every=1000, eval_every=0,
                      dropout=0.0, seed=100 + part,
                      ckpt_dir=os.path.join(ws, "ckpt", f"part-{{part}}"),
                      ckpt_every=2)
    tr = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                 dropout=0.0), ds.graph, cfg,
                        train_ids=ids[part::{num_parts}])
    try:
        out = tr.train()
    except Preempted:
        raise SystemExit(75)
    except NumericsFault:
        # the sentry halted cleanly; the quarantine + workspace marker
        # already landed — exit retryable so the driver rolls back
        raise SystemExit(NUMERICS_FAULT_EXIT)
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out["params"]):
        h.update(np.asarray(leaf).tobytes())
    with open(os.path.join(r"{result_dir}", f"result-{{part}}.json"),
              "w") as f:
        json.dump({{"part": part, "step": out["step"],
                    "digest": h.hexdigest()}}, f)
"""


def run_once(part: int, sentry: bool, epochs: int = EPOCHS):
    """One in-process seeded run; returns (digest, warm seeds/sec —
    the median over post-compile epochs, the bench_scaling warm-epoch
    protocol — and the jit-compile delta)."""
    import statistics

    import jax

    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.obs import get_obs
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig

    def compiles() -> int:
        fam = get_obs().metrics.snapshot().get(
            "jit_compiles_total") or {}
        return int(sum(s.get("value", 0)
                       for s in fam.get("samples", [])))

    ds = datasets.synthetic_node_clf(num_nodes=240, num_edges=1200,
                                     feat_dim=8, num_classes=4, seed=3)
    ids = np.nonzero(ds.graph.ndata["train_mask"])[0]
    cfg = TrainConfig(num_epochs=epochs, batch_size=BATCH,
                      fanouts=(3, 3), log_every=1000, eval_every=0,
                      dropout=0.0, seed=100 + part, sentry=sentry)
    c0 = compiles()
    out = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                  dropout=0.0), ds.graph, cfg,
                         train_ids=ids[part::NUM_PARTS]).train()
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out["params"]):
        h.update(np.asarray(leaf).tobytes())
    warm = [r["seeds_per_sec"] for r in out["history"][1:]] \
        or [out["history"][-1]["seeds_per_sec"]]
    return h.hexdigest(), statistics.median(warm), compiles() - c0


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="quality_smoke_")
    try:
        # ---- act 1: sentry on == sentry off, overhead measured -----
        # 6 epochs, warm-epoch median: compile cost must not pollute
        # the overhead claim (digest parity is checked on the SAME
        # epoch count, so it still pins the full trajectory)
        d_off, sps_off, comp_off = run_once(0, sentry=False,
                                            epochs=OVERHEAD_EPOCHS)
        d_on, sps_on, comp_on = run_once(0, sentry=True,
                                         epochs=OVERHEAD_EPOCHS)
        assert d_on == d_off, \
            "sentry-on trajectory diverged from sentry-off"
        assert comp_on == comp_off, \
            f"stats pytree added a recompile ({comp_on} vs {comp_off})"
        overhead = 1.0 - sps_on / max(sps_off, 1e-9)
        record = {"metric": "quality",
                  "sentry_on_seeds_per_sec": round(sps_on, 1),
                  "sentry_off_seeds_per_sec": round(sps_off, 1),
                  "sentry_overhead_frac": round(overhead, 4),
                  "bit_identical": True,
                  "jit_compiles_on": comp_on,
                  "jit_compiles_off": comp_off,
                  "parts": NUM_PARTS, "epochs": OVERHEAD_EPOCHS,
                  "batch_size": BATCH}
        if os.environ.get("QUALITY_UPDATE"):
            path = os.path.join(_REPO, "benchmarks", "QUALITY.json")
            with open(path, "w") as f:
                json.dump(record, f, indent=2, sort_keys=True)
                f.write("\n")

        # ---- act 2: chaos numerics:nan -> halt -> rollback ----------
        ws = os.path.join(tmp, "ws")
        conf = os.path.join(tmp, "conf")
        os.makedirs(ws)
        os.makedirs(conf)
        g = datasets.karate_club().graph
        partition_graph(g, "karate", NUM_PARTS,
                        os.path.join(ws, "dataset"))
        write_hostfile(os.path.join(conf, "hostfile"),
                       [HostEntry(f"10.0.0.{i}", 30070 + i,
                                  f"w{i}-worker", 1)
                        for i in range(NUM_PARTS)])
        entry = os.path.join(tmp, "train.py")
        with open(entry, "w") as f:
            f.write(textwrap.dedent(ENTRY.format(
                result_dir=tmp, num_parts=NUM_PARTS)))
        base = {p: run_once(p, sentry=True) for p in range(NUM_PARTS)}
        ds = datasets.synthetic_node_clf(num_nodes=240, num_edges=1200,
                                         feat_dim=8, num_classes=4,
                                         seed=3)
        ids = np.nonzero(ds.graph.ndata["train_mask"])[0]
        steps_per_epoch = max(len(ids[1::NUM_PARTS]) // BATCH, 1)
        assert steps_per_epoch >= 3, "inject step must land mid-train"
        inject = steps_per_epoch + 1

        os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)
        os.environ.pop("TPU_OPERATOR_OBS_DIR", None)
        os.environ[chaos.CHAOS_ENV] = f"numerics:nan:{inject}"
        os.environ["TPU_OPERATOR_RETRY_BASE_S"] = "0.05"
        argv = ["--graph-name", "karate",
                "--num-partitions", str(NUM_PARTS),
                "--train-entry-point", entry, "--workspace", ws,
                "--conf-dir", conf, "--num-epochs", str(EPOCHS),
                "--batch-size", str(BATCH), "--fabric", "local",
                "--numerics-retries", "1"]
        tpurun.main(argv)       # must complete despite the poisoning

        for p in range(NUM_PARTS):
            out = json.loads(open(os.path.join(
                tmp, f"result-{p}.json")).read())
            assert out["digest"] == base[p][0], \
                f"part {p}: post-rollback params diverged from the " \
                "undisturbed run"

        evs = [json.loads(ln) for ln in
               open(os.path.join(ws, "obs", "events.jsonl"))]
        kinds = [e["event"] for e in evs]
        for k in ("chaos_numerics_nan", "numerics_fault",
                  "numerics_halt", "ckpt_quarantined",
                  "numerics_rollback", "train_resume"):
            assert k in kinds, f"missing event {k}"
        fault = next(e for e in evs if e["event"] == "numerics_fault")
        assert fault["step"] == inject + 1, fault
        assert fault["partition"] is not None, fault
        # the quarantine rolled back BELOW the fault step
        quar = next(e for e in evs if e["event"] == "ckpt_quarantined")
        assert quar["rolled_back_to"] is None \
            or quar["rolled_back_to"] <= inject, quar
        resume = [e for e in evs if e["event"] == "train_resume"]
        assert resume and all(e["step"] <= inject for e in resume)

        # ---- act 3: the doctor tells the story ---------------------
        from dgl_operator_tpu.obs import doctor
        rc = doctor.main([os.path.join(ws, "obs")])
        report = json.load(open(os.path.join(ws, "obs", "job",
                                             "report.json")))
        mh = report["model_health"]
        assert mh["faults"] and mh["rollbacks"] >= 1, mh
        assert mh["faults"][0]["step"] == inject + 1, mh
        found = [f for f in report["findings"]
                 if f["kind"] == "numerics_fault"]
        assert found, report["findings"]
        assert all(f["severity"] == "warning" for f in found), found
        assert all(f["evidence"]["step"] == inject + 1
                   and f["evidence"]["partition"] is not None
                   for f in found), found
        assert rc == 0, "a handled numerics fault must not read " \
            "critical"

        print(json.dumps({
            **record, "metric": "quality_smoke", "ok": True,
            "inject_step": inject, "fault_step": fault["step"],
            "fault_partition": fault["partition"],
            "rollbacks": mh["rollbacks"],
            "doctor_rc": rc}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        for k in (chaos.CHAOS_ENV, "TPU_OPERATOR_WORKSPACE"):
            os.environ.pop(k, None)


if __name__ == "__main__":
    main()
