"""`make ooc` smoke: the papers100M-scale data plane end to end
(ISSUE 17, docs/dataplane.md).

One CPU-only run must show

1. **chunked ingestion**: the power-law generator streams edges and
   features to disk (graph/ooc.py ``ChunkedEdgeWriter``) and the
   resulting Graph is mmap-backed — nothing forced the edge list or
   the feature matrix resident;
2. **out-of-core partitioning**: ``partition_graph(ooc=True)`` spills
   the multilevel coarsening frontier (``ooc_spill_mib`` in the book
   meta) and writes int8 feature codes into standalone mmap-able
   ``.npy`` files with the global scale/zero sidecar — while staying
   BYTE-IDENTICAL to the flat path on assignments, halo manifest and
   graph arrays (the ooc parity contract);
3. **int8 train bit-stability**: a quantized-book DistTrainer killed
   mid-epoch by the chaos hook resumes in a fresh trainer to final
   params bit-identical to the uninterrupted run — the quantized
   owner store changes bytes-at-rest, never the trajectory contract;
4. **observability**: tpu-doctor renders a ``data :`` block from the
   run's own metrics (graph/featstore.py ``emit_dataplane_gauges``).

Usage:  python hack/ooc_smoke.py        (CPU-only, ~60 s)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="ooc_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

import jax  # noqa: E402, F401 — backend init after env is settled
import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets, quant  # noqa: E402
from dgl_operator_tpu.graph.partition import (GraphPartition,  # noqa: E402
                                              partition_graph)
from dgl_operator_tpu.launcher.chaos import CHAOS_ENV  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.obs.doctor import build_report, render  # noqa: E402
from dgl_operator_tpu.parallel import make_mesh  # noqa: E402
from dgl_operator_tpu.runtime import (DistTrainer, Preempted,  # noqa: E402
                                      TrainConfig)


def main() -> int:
    # 1. chunked ingestion -> mmap-backed dataset (never resident)
    ds = datasets.synthetic_scale_graph(
        3000, 15000, feat_dim=12, num_classes=4, seed=5,
        out_dir=os.path.join(_TMP, "gen"), chunk_edges=4096)
    g = ds.graph
    assert isinstance(g.src.base, np.memmap), "edge list went resident"
    assert isinstance(g.ndata["feat"], np.memmap), "feats went resident"

    # 2. ooc multilevel partition under a working-set budget, int8
    # feature codes — byte-identical partition book vs the flat path
    flat_json = partition_graph(g, "oocsmoke", 2,
                                os.path.join(_TMP, "flat"))
    ooc_json = partition_graph(g, "oocsmoke", 2,
                               os.path.join(_TMP, "ooc"),
                               ooc=True, ooc_budget_mb=128,
                               feat_dtype="int8")
    with open(ooc_json) as f:
        meta = json.load(f)
    assert meta["ooc_spill_mib"] is not None, "frontier never spilled"
    assert meta["feat_quant"]["feat"]["dtype"] == "int8"
    for rel in ("node_map.npy", "edge_map.npy", "part0/graph.npz",
                "part1/graph.npz"):
        a = open(os.path.join(_TMP, "flat", rel), "rb").read()
        b = open(os.path.join(_TMP, "ooc", rel), "rb").read()
        assert a == b, f"ooc parity broken on {rel}"

    # the book's codes round-trip within the affine error bound and
    # the loaded partition demand-pages them (mmap, not resident)
    p0 = GraphPartition(ooc_json, 0)
    codes = p0.graph.ndata["feat"]
    assert isinstance(codes, np.memmap) and codes.dtype == np.int8
    sc = p0.feat_sidecar("feat")
    err = float(np.max(np.abs(
        quant.dequantize(np.asarray(codes), sc["scale"], sc["zero"])
        - np.asarray(g.ndata["feat"])[np.asarray(p0.orig_id)])))
    bound = float(quant.max_abs_error_bound(sc["scale"]).max())
    assert err <= bound + 1e-6, (err, bound)

    # 3. int8 train: chaos kill mid-epoch -> fresh-process resume,
    # bit-identical to the uninterrupted quantized run
    def trainer(ckpt=None):
        cfg = TrainConfig(num_epochs=2, batch_size=32, fanouts=(3, 3),
                          log_every=1000, eval_every=1000, dropout=0.0,
                          seed=0, feat_dtype="int8", ckpt_dir=ckpt)
        return DistTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                    dropout=0.0), ooc_json,
                           make_mesh(num_dp=2), cfg)

    out_ref = trainer().train()
    ckpt_dir = os.path.join(_TMP, "ckpt")
    tr = trainer(ckpt=ckpt_dir)
    steps_per_epoch = max(tr._global_min_train // tr.cfg.batch_size, 1)
    kill = steps_per_epoch + 1            # genuinely mid-epoch 1
    os.environ[CHAOS_ENV] = f"train:kill:{kill}"
    try:
        tr.train()
        raise AssertionError("chaos kill did not preempt the trainer")
    except Preempted:
        pass
    finally:
        del os.environ[CHAOS_ENV]
    out_res = trainer(ckpt=ckpt_dir).train()
    for a, b in zip(jax.tree.leaves(out_ref["params"]),
                    jax.tree.leaves(out_res["params"])):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "int8 kill/resume diverged from the uninterrupted run"

    # 4. the doctor reads the data plane back from the run's metrics
    get_obs().flush()
    report = build_report(os.environ["TPU_OPERATOR_OBS_DIR"])
    text = render(report)
    data_lines = [ln for ln in text.splitlines()
                  if ln.strip().startswith("data")]
    assert data_lines, "tpu-doctor rendered no data block:\n" + text
    assert "int8" in data_lines[0], data_lines

    print(json.dumps({
        "metric": "ooc_smoke",
        "spill_mib": meta["ooc_spill_mib"],
        "quant_max_err": round(err, 5),
        "quant_err_bound": round(bound, 5),
        "resume_from": kill,
        "final_loss": round(float(out_res["history"][-1]["loss"]), 4),
        "doctor_data_line": data_lines[0].strip(),
        "ok": True}))
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)
    sys.exit(rc)
