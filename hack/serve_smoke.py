"""`make serve` smoke: the serving plane end to end on a toy graph.

Drives the full request lifecycle the docs promise (docs/serving.md):
partition a synthetic graph, train a couple of epochs with the
DistTrainer, export the params-only serving artifact, boot the
AOT-warmed engine + micro-batcher + HTTP front end, fire CONCURRENT
requests at /predict, and assert:

- responses are well-formed and bit-consistent with the trainer's
  predict() seam for the same seed nodes;
- /healthz reports the warmed engine;
- /metrics exposes the serve SLO catalogue (request latency histogram,
  batch occupancy, cache hit/remote counters);
- tpu-doctor's report over the run carries the serving SLO block.

Usage:  python hack/serve_smoke.py        (CPU-only, ~1 min)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs, obs_run  # noqa: E402


def main() -> None:
    import jax

    from dgl_operator_tpu.parallel import make_mesh
    from dgl_operator_tpu.runtime import DistTrainer, TrainConfig
    from dgl_operator_tpu.runtime.checkpoint import (export_for_serving,
                                                     load_params)
    from dgl_operator_tpu.serve.engine import ServeConfig, ServeEngine
    from dgl_operator_tpu.serve.server import ServingPlane

    tmp = tempfile.mkdtemp(prefix="serve_smoke_")
    obs_dir = os.path.join(tmp, "obs")
    with obs_run(obs_dir, role="serve-smoke"):
        ds = datasets.synthetic_node_clf(num_nodes=600, num_edges=3000,
                                         feat_dim=16, num_classes=4,
                                         seed=3)
        cfg_json = partition_graph(ds.graph, "smoke", 4,
                                   os.path.join(tmp, "parts"))
        model = DistSAGE(hidden_feats=16, out_feats=4, dropout=0.0)
        tcfg = TrainConfig(num_epochs=2, batch_size=16, lr=0.01,
                           fanouts=(3, 3), log_every=1000, eval_every=0,
                           cap_policy="worst")
        tr = DistTrainer(model, cfg_json, make_mesh(num_dp=4), tcfg)
        out = tr.train()
        params = jax.device_get(out["params"])
        export = export_for_serving(os.path.join(tmp, "serving.npz"),
                                    params)

        scfg = ServeConfig(fanouts=(3, 3), batch_size=16,
                           cap_policy="worst", max_wait_ms=2.0)
        engine = ServeEngine(model, cfg_json, params=load_params(export),
                             cfg=scfg)
        assert engine.warm_shapes == 1 and engine.warmup_seconds > 0
        plane = ServingPlane(engine, port=0).start()
        url = f"http://127.0.0.1:{plane.port}"
        try:
            rng = np.random.default_rng(0)
            results = {}

            def fire(i):
                ids = rng.choice(ds.graph.num_nodes, size=3,
                                 replace=False).tolist()
                req = urllib.request.Request(
                    url + "/predict",
                    data=json.dumps({"nodes": ids}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    results[i] = (ids, json.load(r))

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 8, "a concurrent request was lost"
            for ids, resp in results.values():
                assert len(resp["predictions"]) == len(ids)
                assert resp["latency_ms"] > 0

            hz = json.load(urllib.request.urlopen(url + "/healthz",
                                                  timeout=10))
            assert hz["ok"] and hz["parts"] == 4 and hz["warm_shapes"]

            met = urllib.request.urlopen(url + "/metrics",
                                         timeout=10).read().decode()
            for fam in ("serve_request_seconds", "serve_batches_total",
                        "serve_batch_occupancy",
                        "serve_halo_cache_hits_total"):
                assert fam in met, f"{fam} missing from /metrics"

            # bit-consistency spot check against the trainer's seam:
            # the engine answers a direct predict() with the same
            # sample stream identically
            seeds = np.asarray(sorted(results[0][0]), np.int64)
            lg_e = engine.predict_logits(seeds, sample_seed=99)
            lg_t = tr.predict(params, seeds, sample_seed=99)
            assert np.array_equal(lg_e, lg_t), \
                "server forward drifted from trainer forward"
        finally:
            plane.stop()
        get_obs().flush()

    # the doctor reads the finished run's artifacts and renders the
    # serving SLO block
    from dgl_operator_tpu.obs.doctor import build_report, render

    report = build_report(obs_dir)
    slo = report.get("serve_slo")
    assert slo and slo["requests"] >= 8 and slo["p50_ms"] is not None, \
        f"doctor missed the serving plane: {slo}"
    text = render(report)
    assert "serving" in text and "latency p50" in text
    print(text)
    print("serve smoke OK:", json.dumps(slo))


if __name__ == "__main__":
    main()
