"""`make zero3` smoke: ZeRO-3 persistent param sharding end to end
(ISSUE 16, docs/sharding.md).

A 2x2-mesh (dp=2 x mp=2) DistTrainer run under ``zero_stage=3`` with a
tensor-parallel rule on the dense kernels must

1. persist strictly fewer parameter bytes per device than the
   replicated baseline — checked BOTH analytically
   (``state_sharding`` summary) and against the real per-device buffer
   shards of the live storage arrays;
2. fuse the param all-gathers into the step: the obs trace carries
   ``param_gather_fused`` spans and the epoch history records a
   ``param_gather_overlap_ratio``;
3. survive a mid-train SIGTERM: the chaos hook kills the first zero-3
   trainer mid-epoch, its flush writes the LOGICAL (mesh-shape-
   invariant) state, and a FRESH trainer resumes to final params
   bit-identical to the uninterrupted zero-3 run (and allclose to the
   replicated run — the reduce-scatter algebra is the replicated
   math's, modulo collective summation order).

Usage:  python hack/zero3_smoke.py        (CPU-only, ~60 s)
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
_TMP = tempfile.mkdtemp(prefix="zero3_smoke_")
os.environ["TPU_OPERATOR_OBS_DIR"] = os.path.join(_TMP, "obs")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher.chaos import CHAOS_ENV  # noqa: E402
from dgl_operator_tpu.models.sage import DistSAGE  # noqa: E402
from dgl_operator_tpu.obs import get_obs  # noqa: E402
from dgl_operator_tpu.parallel import MP_AXIS, make_train_mesh  # noqa: E402
from dgl_operator_tpu.runtime import (DistTrainer, Preempted,  # noqa: E402
                                      TrainConfig)

# dense kernels shard their output dim over the mp axis; biases (and
# everything else) fall through to the flat dp-shard storage plan
TP_RULES = ((r".*kernel$", (None, MP_AXIS)), (".*", None))


def main() -> int:
    ds = datasets.synthetic_node_clf(num_nodes=400, num_edges=2000,
                                     feat_dim=8, num_classes=4, seed=3)
    cfg_json = partition_graph(ds.graph, "z3smoke", 2,
                               os.path.join(_TMP, "parts"))

    def trainer(zero_stage, ckpt=None):
        cfg = TrainConfig(num_epochs=2, batch_size=16, fanouts=(3, 3),
                          log_every=1000, eval_every=1000, dropout=0.0,
                          seed=0, zero_stage=zero_stage,
                          tp_axis_size=(2 if zero_stage == 3 else 1),
                          shard_rules=(TP_RULES if zero_stage == 3
                                       else None),
                          ckpt_dir=ckpt)
        return DistTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                    dropout=0.0), cfg_json,
                           make_train_mesh(2, 2), cfg)

    # replicated baseline + uninterrupted zero-3 reference
    out_rep = trainer(1).train()
    out_z3 = trainer(3).train()

    # 1. residency: live per-device storage bytes AND the analytic bill
    dev_rep = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                  for x in jax.tree.leaves(out_rep["params"]))
    dev_z3 = sum(int(x.addressable_shards[0].data.nbytes)
                 for x in jax.tree.leaves(out_z3["params_storage"]))
    assert dev_z3 < dev_rep, (dev_z3, dev_rep)
    s_rep = out_rep["state_sharding"]
    s_z3 = out_z3["state_sharding"]
    assert (s_z3["params_mib_per_slot_sharded"]
            < s_rep["params_mib_per_slot_replicated"]), (s_z3, s_rep)

    # 2. the fused gather window shows up in the obs plane
    pratio = out_z3["history"][-1].get("param_gather_overlap_ratio")
    assert pratio is not None and pratio > 0.0, out_z3["history"][-1]
    get_obs().flush()
    spans = []
    for path in glob.glob(os.path.join(_TMP, "obs", "**", "trace.json"),
                          recursive=True):
        with open(path) as f:
            spans += [e for e in json.load(f).get("traceEvents", [])
                      if e.get("name") == "param_gather_fused"]
    assert spans, "no param_gather_fused spans in the obs trace"
    assert all(s.get("cat") == "shard" for s in spans)

    # 3. SIGTERM mid-epoch -> flush -> fresh-process resume, bit-exact
    ckpt_dir = os.path.join(_TMP, "ckpt")
    tr = trainer(3, ckpt=ckpt_dir)
    steps_per_epoch = max(tr._global_min_train
                          // tr.cfg.batch_size, 1)
    kill = steps_per_epoch + 1            # genuinely mid-epoch 1
    os.environ[CHAOS_ENV] = f"train:kill:{kill}"
    try:
        tr.train()
        raise AssertionError("chaos kill did not preempt the trainer")
    except Preempted:
        pass
    finally:
        del os.environ[CHAOS_ENV]
    out_res = trainer(3, ckpt=ckpt_dir).train()
    for a, b in zip(jax.tree.leaves(out_z3["params"]),
                    jax.tree.leaves(out_res["params"])):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "zero-3 kill/resume diverged from the uninterrupted run"
    for a, b in zip(jax.tree.leaves(out_rep["params"]),
                    jax.tree.leaves(out_res["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-6)

    print(json.dumps({
        "metric": "zero3_smoke",
        "params_mib_per_slot_replicated":
            s_rep["params_mib_per_slot_replicated"],
        "params_mib_per_slot_zero3":
            s_z3["params_mib_per_slot_sharded"],
        "device_param_bytes_ratio": round(dev_z3 / dev_rep, 4),
        "param_gather_overlap_ratio": pratio,
        "gather_spans": len(spans),
        "resume_from": kill,
        "ok": True}))
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    finally:
        shutil.rmtree(_TMP, ignore_errors=True)
    sys.exit(rc)
