"""`make elastic` smoke: the full elastic fault-domain lifecycle on a
4-host LocalFabric (docs/elasticity.md, ISSUE 13).

Acts:
1. undisturbed baseline — the 4 partition trainers run in-process
   with the exact seeds/streams the e2e entry uses; final-param
   sha256 digests are the ground truth;
2. chaos ``host:die`` mid-train under ``tpurun --elastic`` — the
   driver must shrink (re-place the dead host's partition over the
   3 survivors, fenced epoch bump, relaunch from checkpoint) and the
   job must COMPLETE at reduced width with every partition's params
   bit-equal to the baseline;
3. regrow on readmission — clearing the dead marker and relaunching
   must re-place back to full width under a fresh epoch;
4. ``tpu-doctor`` must render the elasticity block (dead host,
   shrink + regrow, fence state) with the handled death as a
   warning, not a critical.

Usage:  python hack/elastic_smoke.py        (CPU-only, ~2 min)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# tests and smoke drives share the virtual-CPU-mesh environment rules
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
pp = os.environ.get("PYTHONPATH", "")
if _REPO not in pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _REPO + (os.pathsep + pp if pp else "")

import numpy as np  # noqa: E402

from dgl_operator_tpu.graph import datasets  # noqa: E402
from dgl_operator_tpu.graph.partition import partition_graph  # noqa: E402
from dgl_operator_tpu.launcher import chaos, elastic, tpurun  # noqa: E402
from dgl_operator_tpu.parallel.bootstrap import (HostEntry,  # noqa: E402
                                                 parse_hostfile,
                                                 write_hostfile)

NUM_PARTS = 4
EPOCHS = 2
BATCH = 16
DEAD_HOST = "w3-worker"

ENTRY = """
    import argparse, hashlib, json, os
    import numpy as np
    ap = argparse.ArgumentParser()
    for f in ("--graph_name", "--ip_config", "--part_config"):
        ap.add_argument(f)
    for f in ("--num_epochs", "--batch_size", "--num_workers"):
        ap.add_argument(f, type=int)
    a = ap.parse_args()
    import jax
    from dgl_operator_tpu.graph import datasets
    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import (Preempted, SampledTrainer,
                                          TrainConfig)
    # elastic hostfile contract: line i = partition i, so the rank IS
    # the partition; streams are keyed by (step position, partition)
    part = int(os.environ["TPU_OPERATOR_RANK"])
    ws = os.environ["TPU_OPERATOR_WORKSPACE"]
    ds = datasets.synthetic_node_clf(num_nodes=240, num_edges=1200,
                                     feat_dim=8, num_classes=4, seed=3)
    ids = np.nonzero(ds.graph.ndata["train_mask"])[0]
    cfg = TrainConfig(num_epochs=a.num_epochs, batch_size=a.batch_size,
                      fanouts=(3, 3), log_every=1000, eval_every=0,
                      dropout=0.0, seed=100 + part,
                      ckpt_dir=os.path.join(ws, "ckpt", f"part-{{part}}"),
                      ckpt_every=2)
    tr = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                 dropout=0.0), ds.graph, cfg,
                        train_ids=ids[part::{num_parts}])
    try:
        out = tr.train()
    except Preempted:
        raise SystemExit(75)
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out["params"]):
        h.update(np.asarray(leaf).tobytes())
    with open(os.path.join(r"{result_dir}", f"result-{{part}}.json"),
              "w") as f:
        json.dump({{"part": part, "step": out["step"],
                    "digest": h.hexdigest()}}, f)
"""


def baseline(part: int):
    """The undisturbed same-seed trainer, in process (identical math
    to the entry — checkpoint knobs are math-inert)."""
    import jax

    from dgl_operator_tpu.models.sage import DistSAGE
    from dgl_operator_tpu.runtime import SampledTrainer, TrainConfig
    ds = datasets.synthetic_node_clf(num_nodes=240, num_edges=1200,
                                     feat_dim=8, num_classes=4, seed=3)
    ids = np.nonzero(ds.graph.ndata["train_mask"])[0]
    mine = ids[part::NUM_PARTS]
    cfg = TrainConfig(num_epochs=EPOCHS, batch_size=BATCH,
                      fanouts=(3, 3), log_every=1000, eval_every=0,
                      dropout=0.0, seed=100 + part)
    out = SampledTrainer(DistSAGE(hidden_feats=8, out_feats=4,
                                  dropout=0.0), ds.graph, cfg,
                         train_ids=mine).train()
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out["params"]):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest(), out["step"], len(mine)


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="elastic_smoke_")
    try:
        ws = os.path.join(tmp, "ws")
        conf = os.path.join(tmp, "conf")
        os.makedirs(ws)
        os.makedirs(conf)
        g = datasets.karate_club().graph
        partition_graph(g, "karate", NUM_PARTS,
                        os.path.join(ws, "dataset"))
        write_hostfile(os.path.join(conf, "hostfile"),
                       [HostEntry(f"10.0.0.{i}", 30050 + i,
                                  f"w{i}-worker", 1)
                        for i in range(NUM_PARTS)])
        entry = os.path.join(tmp, "train.py")
        with open(entry, "w") as f:
            f.write(textwrap.dedent(ENTRY.format(
                result_dir=tmp, num_parts=NUM_PARTS)))
        argv = ["--graph-name", "karate",
                "--num-partitions", str(NUM_PARTS),
                "--train-entry-point", entry, "--workspace", ws,
                "--conf-dir", conf, "--num-epochs", str(EPOCHS),
                "--batch-size", str(BATCH), "--fabric", "local",
                "--elastic"]

        # ---- act 1: the undisturbed ground truth -------------------
        base = {p: baseline(p) for p in range(NUM_PARTS)}
        _, _, n3 = base[3]
        steps_per_epoch = max(n3 // BATCH, 1)
        assert steps_per_epoch >= 2, "die step must land mid-train"
        die = steps_per_epoch + 1

        # ---- act 2: host dies mid-train -> elastic shrink ----------
        os.environ.pop("TPU_OPERATOR_PHASE_ENV", None)
        os.environ.pop("TPU_OPERATOR_OBS_DIR", None)
        os.environ[chaos.CHAOS_ENV] = f"host:die:{die}@host={DEAD_HOST}"
        os.environ["TPU_OPERATOR_RETRY_BASE_S"] = "0.05"
        tpurun.main(argv)            # must complete despite the death

        digests = {}
        for p in range(NUM_PARTS):
            out = json.loads(open(os.path.join(
                tmp, f"result-{p}.json")).read())
            digests[p] = out["digest"]
            assert out["digest"] == base[p][0], \
                f"part {p}: post-shrink params diverged from the " \
                "undisturbed run"
            assert out["step"] == base[p][1], f"part {p}: step count"

        plan = elastic.load_plan(ws)
        assert plan["dead"] == [DEAD_HOST], plan
        assert plan["width"] == NUM_PARTS - 1 and plan["epoch"] == 1
        placed = parse_hostfile(os.path.join(ws, "hostfile_elastic"))
        assert len(placed) == NUM_PARTS            # line per partition
        assert DEAD_HOST not in {e.name for e in placed}
        assert len({e.name for e in placed}) == NUM_PARTS - 1

        evs = [json.loads(ln) for ln in
               open(os.path.join(ws, "obs", "events.jsonl"))]
        kinds = [e["event"] for e in evs]
        for k in ("host_died", "elastic_shrink", "ckpt_fenced",
                  "train_resume"):
            assert k in kinds, k
        died = next(e for e in evs if e["event"] == "host_died")
        assert died["host_name"] == DEAD_HOST and died["step"] == die

        # ---- act 3: readmit -> regrow to full width ----------------
        os.environ.pop(chaos.CHAOS_ENV, None)
        chaos.readmit_host(DEAD_HOST, ws)
        tpurun.main(argv)
        plan2 = elastic.load_plan(ws)
        assert plan2["dead"] == [] and plan2["epoch"] == 2, plan2
        evs2 = [json.loads(ln) for ln in
                open(os.path.join(ws, "obs", "events.jsonl"))]
        regrow = [e for e in evs2 if e["event"] == "elastic_regrow"]
        assert regrow and regrow[-1]["hosts"] == [DEAD_HOST]
        assert regrow[-1]["width"] == NUM_PARTS
        # the full-width relaunch reproduced the same params
        for p in range(NUM_PARTS):
            out = json.loads(open(os.path.join(
                tmp, f"result-{p}.json")).read())
            assert out["digest"] == base[p][0], f"part {p} post-regrow"

        # ---- act 4: the doctor tells the story ---------------------
        from dgl_operator_tpu.obs import doctor
        rc = doctor.main([os.path.join(ws, "obs")])
        report = json.load(open(os.path.join(ws, "obs", "job",
                                             "report.json")))
        el = report["elasticity"]
        assert el["dead_hosts"] == [DEAD_HOST], el
        assert el["shrinks"] >= 1 and el["regrows"] >= 1
        assert el["last_epoch"] == 2
        died_f = [f for f in report["findings"]
                  if f["kind"] == "host_died"]
        assert died_f and all(f["severity"] == "warning"
                              for f in died_f), died_f
        assert rc == 0, "handled death must not read critical"

        print(json.dumps({
            "metric": "elastic_smoke", "ok": True,
            "parts": NUM_PARTS, "die_step": die,
            "shrunk_width": plan["width"],
            "epochs": {"shrink": plan["epoch"],
                       "regrow": plan2["epoch"]},
            "bit_identical_parts": sum(
                1 for p in range(NUM_PARTS)
                if digests[p] == base[p][0]),
            "host_deaths": kinds.count("host_died"),
            "shrinks": el["shrinks"], "regrows": el["regrows"],
            "doctor_rc": rc}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        for k in (chaos.CHAOS_ENV, "TPU_OPERATOR_ELASTIC_EPOCH",
                  "TPU_OPERATOR_WORKSPACE"):
            os.environ.pop(k, None)


if __name__ == "__main__":
    main()
